"""Policy-driven pool autoscaling (serving/autoscale.py): the acceptance
drills from docs/serving.md "Autoscaling", all tier-1-fast on CPU.

The headline: under a 4× Poisson flash crowd against a prefill-starved
disaggregated fleet, a rebalanced fleet flips an idle decode replica into
the prefill pool through the drain-safe machinery and beats the fixed
shape on sheds and TTFT p99 — with offered == terminated exact on both
sides. The guardrails ride along: structural hysteresis holds flips to
zero under an oscillating signal, the fail-static rung freezes the shape
(and says why) when the signal source degrades, a chaos kill mid-flip
aborts cleanly with nothing stranded, and a fleet built WITHOUT a
rebalancer keeps its metrics schema byte-identical to before the
subsystem existed. Deadline-aware admission is drilled here too: the
router sheds a request EARLY when the quoted wait exceeds its remaining
deadline budget, priced as its own counter.
"""

import json

import numpy as np
import pytest

import jax

from accelerate_tpu.models import Llama
from accelerate_tpu.resilience import FaultPlan
from accelerate_tpu.serving import (
    AutoscalePolicy,
    QueueFull,
    ReplicaState,
    RoleRebalancer,
    ServingEngine,
    ServingRouter,
    fleet_signals,
    make_burst_trace,
    run_offered_load,
)


@pytest.fixture(scope="module")
def llama():
    model = Llama("llama-tiny")
    return model, model.init(jax.random.key(0))


def _prompts(lengths, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (s,)).astype(np.int32) for s in lengths]


def _fleet(llama, roles=("prefill", "decode", "decode"), autoscale=None,
           fault_plan=None, telemetry=None, tracer=None, **engine_kwargs):
    model, params = llama
    kwargs = {"num_slots": 2, "max_len": 64, **engine_kwargs}
    return ServingRouter(
        engine_factory=lambda: ServingEngine(model, params, **kwargs),
        num_replicas=len(roles),
        roles=list(roles),
        autoscale=autoscale,
        fault_plan=fault_plan,
        telemetry=telemetry,
        tracer=tracer,
    )


def _starved_prefill_reader(router):
    """Synthetic signals: prefill pool starved, decode pool idle — the
    unambiguous flip trigger, decoupled from wall-clock load."""
    return {
        "fleet_step": router._steps,
        "pools": {
            "prefill": {"replicas": 1, "pressure": 5.0},
            "decode": {"replicas": 2, "pressure": 0.0},
        },
    }


def _drain(router, results, bound=500):
    for _ in range(bound):
        if not router.busy:
            return True
        for r in router.step():
            results[r.request_id] = r
    return False


# -- the acceptance drill -----------------------------------------------------


def test_burst_drill_rebalanced_beats_fixed(llama):
    """The tentpole claim: the SAME Poisson burst trace replays against a
    fixed [prefill, decode, decode, decode] fleet and one with the
    rebalancer attached. The rebalanced fleet flips decode replicas into
    the starved prefill pool mid-burst and must strictly beat the fixed
    shape on shed count AND TTFT p99 — while both keep offered ==
    terminated exact and the flip leaves nothing parked behind.

    The load is genuinely PREFILL-bound — chunked prefill makes every
    56-token prompt a 4-step admission while decode is 2 tokens — and the
    burst is a flash crowd (the multiplier collapses the middle half of the
    trace into one clump), so saturation is structural (clump size vs
    admission capacity), not a race against the machine's step speed."""
    n = 80
    prompts = _prompts([56] * n, seed=0)
    arrivals = make_burst_trace(n, 12.0, burst_multiplier=500.0, burst_fraction=0.5, seed=0)

    def fleet(autoscale=None):
        return _fleet(
            llama,
            roles=("prefill", "decode", "decode", "decode"),
            autoscale=autoscale,
            max_queue=2,
            prefill_chunk=16,
        )

    fleet().warmup()  # both measured fleets share the model's jit cache
    fixed = run_offered_load(fleet(), prompts, 2, arrival_times=arrivals)

    # cooldown outlasts the 2x-dwell thrash window: even if the trace's
    # tail argues for a reversal, it cannot land where it would count as
    # thrash — the 0 below is structural, not luck
    rebalancer = RoleRebalancer(
        policy=AutoscalePolicy(cadence_steps=2, min_dwell_steps=8, cooldown_steps=20)
    )
    router = fleet(autoscale=rebalancer)
    rebalanced = run_offered_load(router, prompts, 2, arrival_times=arrivals)

    # offered == terminated, exact, on BOTH sides of the pair
    assert fixed["requests_completed"] == n
    assert rebalanced["requests_completed"] == n
    # the flip genuinely happened, without thrash, and converged
    assert rebalancer.flip_count >= 1
    assert rebalancer.thrash_count == 0
    assert rebalancer._inflight is None
    # nothing stranded: every engine's parked ledger ran dry
    assert all(
        getattr(r.engine, "parked_count", 0) == 0 for r in router.replicas if r.alive
    )
    # the value claim: strictly fewer sheds, strictly lower tail TTFT
    assert rebalanced["loadgen_sheds"] < fixed["loadgen_sheds"]
    assert rebalanced["loadgen_ttft_p99_ms"] < fixed["loadgen_ttft_p99_ms"]
    # a flip reuses the engine's compiled programs: the measured windows
    # (post-warmup) compiled nothing, flips included
    assert rebalanced["compile_count"] == 0
    # gain-schema: the rebalanced fleet's metrics carry the autoscale block
    assert rebalanced["autoscale_flip_count"] == rebalancer.flip_count
    assert rebalanced["autoscale_thrash_count"] == 0


# -- hysteresis ---------------------------------------------------------------


def test_oscillating_signals_hold_shape(llama):
    """Traffic oscillating around the scale-up threshold while the would-be
    donor sits mid-deadband must not move a single replica: the deadband
    needs BOTH a starved pool and an idle donor simultaneously."""
    calls = {"n": 0}

    def oscillating(router):
        calls["n"] += 1
        return {
            "fleet_step": router._steps,
            "pools": {
                # prefill flaps between starved and idle every read...
                "prefill": {"replicas": 1, "pressure": 5.0 if calls["n"] % 2 else 0.0},
                # ...but decode never leaves the middle of the deadband
                "decode": {"replicas": 2, "pressure": 1.0},
            },
        }

    reb = RoleRebalancer(
        policy=AutoscalePolicy(cadence_steps=1, min_dwell_steps=2, cooldown_steps=1),
        signal_reader=oscillating,
    )
    router = _fleet(llama, autoscale=reb)
    for _ in range(30):
        router.step()
    assert reb.evaluations > 0
    assert reb.flip_count == 0
    assert reb.thrash_count == 0
    assert reb.fail_static is False
    assert [r.role for r in router.replicas] == ["prefill", "decode", "decode"]


def test_sustained_starvation_flips_once_then_reverse_is_blocked(llama):
    """Sustained starvation flips exactly one replica (one in-flight
    transition, then the donor-pool floor holds); an immediate signal
    reversal is blocked by the direction dwell — no see-saw, thrash 0."""
    mode = {"reader": _starved_prefill_reader}

    def reader(router):
        return mode["reader"](router)

    reb = RoleRebalancer(
        policy=AutoscalePolicy(cadence_steps=1, min_dwell_steps=8, cooldown_steps=1),
        signal_reader=reader,
    )
    router = _fleet(llama, autoscale=reb)
    # a replica's dwell counts from fleet construction, so the first flip
    # cannot fire before step == min_dwell_steps; step past the gate — the
    # idle donor then starts AND settles the flip within one step
    for _ in range(10):
        router.step()
    assert reb.flip_count == 1
    assert sorted(r.role for r in router.replicas) == ["decode", "prefill", "prefill"]

    def reversed_reader(router):
        return {
            "fleet_step": router._steps,
            "pools": {
                "prefill": {"replicas": 2, "pressure": 0.0},
                "decode": {"replicas": 1, "pressure": 5.0},
            },
        }

    mode["reader"] = reversed_reader
    for _ in range(5):  # all within min_dwell_steps of the flip
        router.step()
    assert reb.flip_count == 1  # the reverse direction never fired
    assert reb.thrash_count == 0


def test_donor_pool_floor_is_checked_against_the_fleet(llama):
    """A lying signal reader claiming the donor pool has spare replicas must
    not drain its last member: the never-empty-a-pool guard runs against
    the fleet's own books, not the reader's claim."""

    def lying(router):
        return {
            "fleet_step": router._steps,
            "pools": {
                "prefill": {"replicas": 1, "pressure": 5.0},
                "decode": {"replicas": 99, "pressure": 0.0},  # the lie
            },
        }

    reb = RoleRebalancer(
        policy=AutoscalePolicy(cadence_steps=1, min_dwell_steps=1, cooldown_steps=1),
        signal_reader=lying,
    )
    router = _fleet(llama, roles=("prefill", "decode"), autoscale=reb)
    for _ in range(10):
        router.step()
    assert reb.flip_count == 0  # decode pool's only member stayed put
    assert [r.role for r in router.replicas] == ["prefill", "decode"]


def test_policy_validation():
    with pytest.raises(ValueError, match="deadband inverted"):
        AutoscalePolicy(scale_up_pressure=1.0, scale_down_pressure=1.5)
    with pytest.raises(ValueError, match=">= 1"):
        AutoscalePolicy(cadence_steps=0)


# -- fail-static --------------------------------------------------------------


def test_chaos_signal_outage_lands_in_fail_static(llama, tmp_path):
    """The signal-outage chaos leg: the rebalancer freezes the fleet's
    shape, records ONE {"kind": "autoscale"} fail_static record naming the
    reason, and the fleet keeps serving its current shape throughout."""
    from accelerate_tpu.telemetry import Telemetry, TelemetryConfig

    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    reb = RoleRebalancer(
        policy=AutoscalePolicy(cadence_steps=1, min_dwell_steps=1, cooldown_steps=1),
        signal_reader=_starved_prefill_reader,
    )
    router = _fleet(
        llama, autoscale=reb,
        fault_plan=FaultPlan(autoscale_outage_step=0), telemetry=hub,
    )
    rids = [router.submit(p, max_new_tokens=3) for p in _prompts([5, 7], seed=1)]
    results = {}
    assert _drain(router, results)
    assert sorted(results) == sorted(rids)  # frozen shape still serves
    assert reb.fail_static is True
    assert reb.fail_static_count == 1  # one episode, not one per step
    assert "chaos" in reb.fail_static_reason
    assert reb.flip_count == 0  # starvation signals ignored while frozen
    m = router.metrics()
    assert m["autoscale_fail_static"] is True
    assert m["autoscale_fail_static_reason"] == reb.fail_static_reason
    hub.finish(flush=False)
    records = [json.loads(line) for line in open(tmp_path / "telemetry.jsonl")]
    frozen = [r for r in records if r.get("kind") == "autoscale"
              and r.get("event") == "fail_static"]
    assert len(frozen) == 1
    assert "chaos" in frozen[0]["reason"]


def test_fail_static_clears_when_signals_recover(llama, tmp_path):
    """A bounded outage: the rebalancer freezes for its duration, records
    the clearing edge when reads recover, and resumes flipping."""
    from accelerate_tpu.telemetry import Telemetry, TelemetryConfig

    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    reb = RoleRebalancer(
        policy=AutoscalePolicy(cadence_steps=1, min_dwell_steps=1, cooldown_steps=1),
        signal_reader=_starved_prefill_reader,
    )
    router = _fleet(
        llama, autoscale=reb,
        fault_plan=FaultPlan(autoscale_outage_step=0, autoscale_outage_duration=3),
        telemetry=hub,
    )
    for _ in range(8):
        router.step()
    assert reb.fail_static is False
    assert reb.fail_static_count == 1
    assert reb.flip_count >= 1  # decisions resumed after the outage window
    hub.finish(flush=False)
    records = [json.loads(line) for line in open(tmp_path / "telemetry.jsonl")]
    events = [r["event"] for r in records if r.get("kind") == "autoscale"]
    assert events.index("fail_static") < events.index("fail_static_cleared")
    cleared = next(r for r in records if r.get("event") == "fail_static_cleared")
    assert "chaos" in cleared["was"]


def test_raising_signal_reader_freezes_not_crashes(llama):
    """A reader that raises is a degraded signal source, not a fleet
    outage: step() keeps working, the shape freezes, the reason names the
    exception."""

    def broken(router):
        raise RuntimeError("telemetry store unreachable")

    reb = RoleRebalancer(signal_reader=broken)
    router = _fleet(llama, autoscale=reb)
    rids = [router.submit(p, max_new_tokens=3) for p in _prompts([5], seed=2)]
    results = {}
    assert _drain(router, results)
    assert sorted(results) == sorted(rids)
    assert reb.fail_static is True
    assert "RuntimeError" in reb.fail_static_reason


def test_stale_rollup_freezes(llama):
    """A rollup whose fleet_step stamp lags beyond stale_after_steps is not
    trusted: frozen, with the staleness in the reason."""

    def stale(router):
        return {"fleet_step": 0, "pools": _starved_prefill_reader(router)["pools"]}

    reb = RoleRebalancer(
        policy=AutoscalePolicy(
            cadence_steps=1, min_dwell_steps=1, cooldown_steps=1, stale_after_steps=2
        ),
        signal_reader=stale,
    )
    router = _fleet(llama, autoscale=reb)
    for _ in range(6):
        router.step()
    assert reb.fail_static is True
    assert "stale" in reb.fail_static_reason
    assert reb.flip_count <= 1  # only while the stamp was still fresh


# -- chaos: mid-flip kill -----------------------------------------------------


def test_mid_flip_kill_aborts_and_converges(llama):
    """ACCELERATE_CHAOS_REBALANCE_FAIL_AT kills the donor mid-flip: the
    flip aborts (no livelock, in-flight slot released), the router's
    ordinary death machinery re-homes everything, no parked KV is
    stranded, and offered == terminated holds exactly."""
    reb = RoleRebalancer(
        policy=AutoscalePolicy(cadence_steps=1, min_dwell_steps=1, cooldown_steps=1),
        signal_reader=_starved_prefill_reader,
    )
    router = _fleet(
        llama, autoscale=reb, fault_plan=FaultPlan(rebalance_fail_at=(0,)),
    )
    rids = [router.submit(p, max_new_tokens=4) for p in _prompts([6, 9, 5, 7], seed=3)]
    results = {}
    assert _drain(router, results), "mid-flip kill livelocked the fleet"
    assert sorted(results) == sorted(rids)  # terminated exactly once each
    assert reb.aborted_flips == 1
    assert reb._inflight is None
    dead = [r for r in router.replicas if not r.alive]
    assert len(dead) == 1 and "mid role-flip" in dead[0].death_reason
    # the surviving decode replica is the pool's last member: the floor
    # guard holds it, so the fleet converges instead of flip-looping
    assert reb.flip_count == 0
    assert all(
        getattr(r.engine, "parked_count", 0) == 0 for r in router.replicas if r.alive
    )
    assert [e["fault"] for e in router.chaos.events if e["fault"] == "rebalance_fail"]


def test_autoscale_chaos_env_vars(monkeypatch):
    """The new legs arm from the environment like every other chaos leg."""
    monkeypatch.setenv("ACCELERATE_CHAOS_REBALANCE_FAIL_AT", "0,2")
    monkeypatch.setenv("ACCELERATE_CHAOS_AUTOSCALE_OUTAGE_STEP", "5")
    monkeypatch.setenv("ACCELERATE_CHAOS_AUTOSCALE_OUTAGE_DURATION", "3")
    plan = FaultPlan.from_env()
    assert plan is not None and plan.active
    assert plan.rebalance_fail_at == (0, 2)
    assert plan.rebalance_fail(0) is True
    assert plan.rebalance_fail(1) is False
    assert plan.autoscale_outage(4) is False
    assert plan.autoscale_outage(5) is True
    assert plan.autoscale_outage(7) is True
    assert plan.autoscale_outage(8) is False  # duration elapsed
    faults = [e["fault"] for e in plan.events]
    assert "rebalance_fail" in faults and "autoscale_outage" in faults


# -- deadline-aware admission -------------------------------------------------


def test_deadline_admission_sheds_early(llama, tmp_path):
    """A request whose quoted queue wait exceeds its remaining deadline
    budget sheds at SUBMIT — before burning a prefill — and is priced as
    its own counter with its own telemetry reason."""
    from accelerate_tpu.telemetry import Telemetry, TelemetryConfig

    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    router = _fleet(llama, roles=("mixed",), max_queue=4, telemetry=hub)
    prompts = _prompts([6, 6, 6, 6], seed=4)
    # fill both slots and put one in the queue: the gate only fires where
    # the request would actually WAIT
    router.submit(prompts[0], max_new_tokens=24)
    router.submit(prompts[1], max_new_tokens=24)
    router.step()
    router.submit(prompts[2], max_new_tokens=24)
    assert router.replicas[0].engine.scheduler.waiting == 1
    with pytest.raises(QueueFull, match="deadline-aware admission"):
        router.submit(prompts[3], max_new_tokens=24, deadline_s=1e-6)
    assert router.router_deadline_sheds == 1
    assert router.metrics()["router_deadline_sheds"] == 1
    # the control: the SAME request without a deadline is admitted
    rid = router.submit(prompts[3], max_new_tokens=24)
    assert rid in router._inflight
    hub.finish(flush=False)
    records = [json.loads(line) for line in open(tmp_path / "telemetry.jsonl")]
    shed = [r for r in records if r.get("event") == "shed"]
    assert len(shed) == 1 and shed[0]["reason"] == "deadline"
    assert shed[0]["retry_after_s"] > 0
    assert shed[0]["deadline_s"] == 1e-6


def test_deadline_gate_skips_idle_fleet(llama):
    """An idle replica serves immediately whatever the hint formula says:
    the gate must not early-shed against an empty queue (that way lies a
    shed-forever livelock — the engine's own deadline expiry is the honest
    terminal state)."""
    router = _fleet(llama, roles=("mixed",))
    rid = router.submit(_prompts([6], seed=5)[0], max_new_tokens=8, deadline_s=1e-6)
    assert router.router_deadline_sheds == 0
    results = {}
    assert _drain(router, results)
    assert results[rid].finish_reason == "expired"


# -- shed-hint pricing --------------------------------------------------------


def test_no_placeable_hint_prices_draining_at_drain_eta(llama):
    """The shed-quote regression: with every replica DRAINING mid-work, the
    retry_after_s hint must quote the drain ETA (active slots running to
    completion), not the optimistic one-queue-position hint of a replica
    that admits nothing."""
    router = _fleet(llama, roles=("mixed", "mixed"))
    prompts = _prompts([6, 6], seed=6)
    router.submit(prompts[0], max_new_tokens=24)
    router.submit(prompts[1], max_new_tokens=24)
    router.step()  # both replicas have active slots and step stats
    router.drain_replica(0)
    router.drain_replica(1)
    with pytest.raises(QueueFull) as exc_info:
        router.submit(_prompts([5], seed=7)[0], max_new_tokens=4)
    expected = min(r.engine.drain_eta_hint() for r in router.replicas)
    assert exc_info.value.retry_after_s == pytest.approx(expected)
    assert exc_info.value.retry_after_s > 0


# -- schema parity ------------------------------------------------------------


def test_autoscale_none_keeps_schema_byte_identical(llama, tmp_path):
    """A fleet built without a rebalancer (the default) must emit NO
    autoscale_* metrics keys and NO {"kind": "autoscale"} records — the
    subsystem is gain-only, invisible until attached."""
    from accelerate_tpu.telemetry import Telemetry, TelemetryConfig

    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    router = _fleet(llama, telemetry=hub)
    rids = [router.submit(p, max_new_tokens=3) for p in _prompts([5, 8], seed=8)]
    results = {}
    assert _drain(router, results)
    assert sorted(results) == sorted(rids)
    m = router.metrics()
    assert not any(k.startswith("autoscale_") for k in m)
    # deadline pricing is always-on router admission, not autoscale gain
    assert m["router_deadline_sheds"] == 0
    router.flush_telemetry()
    hub.finish(flush=False)
    records = [json.loads(line) for line in open(tmp_path / "telemetry.jsonl")]
    assert not any(r.get("kind") == "autoscale" for r in records)


# -- signals ------------------------------------------------------------------


def test_fleet_signals_rollup_shape(llama):
    """The default signal read: per-pool pressure off the fleet's own
    books, stamped with the fleet step, pending demand attributed by
    phase."""
    router = _fleet(llama)
    rid = router.submit(_prompts([6], seed=9)[0], max_new_tokens=4)
    router.step()  # prefill + park: the request is now decode-pool demand
    signals = fleet_signals(router)
    assert signals["fleet_step"] == router._steps
    assert set(signals["pools"]) == {"prefill", "decode"}
    for pool in signals["pools"].values():
        assert pool["replicas"] >= 1
        assert pool["pressure"] >= 0.0
        assert 0.0 <= pool["slot_occupancy"] <= 1.0
    # the parked request awaiting handoff is DECODE demand, not prefill
    assert signals["pools"]["decode"]["pending"] >= 1
    assert signals["pools"]["prefill"]["pending"] == 0
    results = {}
    assert _drain(router, results)
    assert rid in results
