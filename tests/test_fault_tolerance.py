"""Fault-tolerance subsystem: manifest build/verify, atomic commit, torn-dir
GC, transient-I/O retry, preemption flags, rotation-after-commit, auto-resume
(ISSUE 1 tentpole)."""

import errno
import os

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, CheckpointManager
from accelerate_tpu.fault_tolerance import (
    build_manifest,
    commit_checkpoint,
    garbage_collect_torn,
    latest_valid_checkpoint,
    list_checkpoints,
    read_manifest,
    staging_dir_for,
    verify_checkpoint,
    write_manifest,
)
from accelerate_tpu.state import PartialState
from accelerate_tpu.utils.memory import is_transient_io_error, retry_transient_io


class Tiny:
    def init(self, rng):
        return {"w": jax.random.normal(rng, (8, 4), jnp.float32)}

    @staticmethod
    def apply(params, x):
        return x @ params["w"]


def _loss(params, batch):
    return jnp.mean(Tiny.apply(params, batch) ** 2)


def _make_acc():
    acc = Accelerator()
    model = acc.prepare(Tiny())
    opt = acc.prepare_optimizer(optax.sgd(1e-2))
    return acc, model, opt


def _write_dir(tmp_path, name="ckpt", files=("a.bin", "sub/b.bin")):
    d = tmp_path / name
    for rel in files:
        full = d / rel
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_bytes(os.urandom(256))
    return str(d)


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def test_manifest_roundtrip_and_verify_ok(tmp_path):
    PartialState()  # manifest records topology
    d = _write_dir(tmp_path)
    manifest = build_manifest(d, step=7, metadata={"epoch": 2})
    write_manifest(d, manifest)
    assert verify_checkpoint(d) == []
    loaded = read_manifest(d)
    assert loaded["step"] == 7
    assert loaded["metadata"]["epoch"] == 2
    assert set(loaded["files"]) == {"a.bin", os.path.join("sub", "b.bin")}
    assert loaded["topology"]["num_devices"] == jax.device_count()


def test_manifest_catches_truncation_bitrot_and_deletion(tmp_path):
    PartialState()
    d = _write_dir(tmp_path)
    write_manifest(d, build_manifest(d))
    # truncation → size mismatch
    with open(os.path.join(d, "a.bin"), "r+b") as f:
        f.truncate(10)
    assert any("size mismatch" in p for p in verify_checkpoint(d))
    # same-size bit flip → checksum mismatch
    write_manifest(d, build_manifest(d))
    with open(os.path.join(d, "a.bin"), "r+b") as f:
        f.write(b"\x00\x01\x02\x03")
    assert any("checksum mismatch" in p for p in verify_checkpoint(d))
    # deletion → missing file
    write_manifest(d, build_manifest(d))
    os.remove(os.path.join(d, "sub", "b.bin"))
    assert any("missing file" in p for p in verify_checkpoint(d))


def test_verify_rejects_manifestless_and_tmp_dirs(tmp_path):
    d = _write_dir(tmp_path)
    assert any("manifest" in p for p in verify_checkpoint(d))
    staged = _write_dir(tmp_path, name="checkpoint_3.tmp")
    assert any("staging" in p for p in verify_checkpoint(staged))
    assert verify_checkpoint(str(tmp_path / "nope")) != []


def test_verify_checkpoint_without_checksums_still_checks_sizes(tmp_path):
    PartialState()
    d = _write_dir(tmp_path)
    write_manifest(d, build_manifest(d))
    with open(os.path.join(d, "a.bin"), "r+b") as f:
        f.write(b"\xff\xfe\xfd\xfc")  # same size, different bytes
    assert verify_checkpoint(d, check_checksums=False) == []
    with open(os.path.join(d, "a.bin"), "r+b") as f:
        f.truncate(10)
    assert verify_checkpoint(d, check_checksums=False) != []


# ---------------------------------------------------------------------------
# commit + discovery
# ---------------------------------------------------------------------------


def test_commit_replaces_existing_dir_and_cleans_aside(tmp_path):
    old = _write_dir(tmp_path, name="final", files=("old.bin",))
    staged = _write_dir(tmp_path, name="final.tmp", files=("new.bin",))
    assert staging_dir_for(old) == staged
    commit_checkpoint(staged, old)
    assert os.path.exists(os.path.join(old, "new.bin"))
    assert not os.path.exists(os.path.join(old, "old.bin"))
    assert not os.path.exists(staged)
    assert not any(name.endswith((".tmp", ".old")) for name in os.listdir(tmp_path))


def test_kill_between_commit_renames_is_recoverable(tmp_path):
    """A kill after the old dir moved aside but before the staging rename
    leaves BOTH complete copies on disk, and neither is eaten by the torn-dir
    GC (the aside suffix is .old, not the .tmp the GC matches); the next
    commit cleans the aside up."""
    final = str(tmp_path / "ckpt")
    # disk state of the interrupted instant: aside + staging, no final
    _write_dir(tmp_path, name="ckpt.old", files=("old.bin",))
    staged = _write_dir(tmp_path, name="ckpt.tmp", files=("new.bin",))
    garbage_collect_torn(str(tmp_path))  # the next save's GC runs first
    assert (tmp_path / "ckpt.old" / "old.bin").exists()  # old copy SURVIVES
    assert not os.path.exists(staged)  # staging is torn debris, GC'd
    # ... and a completed re-commit clears the stale aside
    staged = _write_dir(tmp_path, name="ckpt.tmp", files=("newer.bin",))
    commit_checkpoint(staged, final)
    assert (tmp_path / "ckpt" / "newer.bin").exists()
    assert not (tmp_path / "ckpt.old").exists()


def test_garbage_collect_torn_only_removes_tmp_dirs(tmp_path):
    _write_dir(tmp_path, name="checkpoint_1")
    _write_dir(tmp_path, name="checkpoint_2.tmp")
    _write_dir(tmp_path, name="other.tmp")
    removed = garbage_collect_torn(str(tmp_path))
    assert len(removed) == 2
    assert (tmp_path / "checkpoint_1").exists()
    assert not (tmp_path / "checkpoint_2.tmp").exists()


def test_latest_valid_skips_torn_and_orders_numerically(tmp_path):
    PartialState()
    for step in (1, 2, 10):  # 10 > 2 numerically though "10" < "2" lexically
        d = _write_dir(tmp_path, name=f"checkpoint_{step}")
        write_manifest(d, build_manifest(d, step=step))
    assert list_checkpoints(str(tmp_path))[-1].endswith("checkpoint_10")
    assert latest_valid_checkpoint(str(tmp_path)).endswith("checkpoint_10")
    # tear the newest: discovery falls back to checkpoint_2
    os.remove(os.path.join(str(tmp_path / "checkpoint_10"), "a.bin"))
    assert latest_valid_checkpoint(str(tmp_path)).endswith("checkpoint_2")
    assert latest_valid_checkpoint(str(tmp_path / "empty-nowhere")) is None


# ---------------------------------------------------------------------------
# transient-I/O retry
# ---------------------------------------------------------------------------


def test_transient_io_classifier():
    assert is_transient_io_error(OSError(errno.EIO, "Input/output error"))
    assert is_transient_io_error(OSError(errno.ESTALE, "Stale file handle"))
    assert is_transient_io_error(RuntimeError("DEADLINE_EXCEEDED while writing"))
    assert is_transient_io_error(RuntimeError("HTTP 429 Too Many Requests"))
    assert not is_transient_io_error(FileNotFoundError(2, "No such file"))
    assert not is_transient_io_error(PermissionError(13, "denied"))
    assert not is_transient_io_error(ValueError("bad value"))
    # errno is authoritative for OSError: a path that CONTAINS marker-like
    # digits must not flip a permanent error to transient
    assert not is_transient_io_error(
        FileNotFoundError(2, "No such file", "/ckpts/checkpoint_4290/model_0.safetensors")
    )
    assert not is_transient_io_error(
        OSError(errno.EACCES, "Permission denied", "/data/Service Unavailable.bin")
    )


def test_retry_transient_io_backs_off_then_succeeds(monkeypatch):
    sleeps = []
    monkeypatch.setattr("accelerate_tpu.utils.memory.time.sleep", sleeps.append)
    calls = {"n": 0}

    @retry_transient_io(base_delay=0.1)
    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(errno.EIO, "Input/output error")
        return "ok"

    assert flaky() == "ok"
    assert calls["n"] == 3
    assert sleeps == [0.1, 0.2]  # exponential backoff


def test_retry_transient_io_propagates_non_transient(monkeypatch):
    monkeypatch.setattr("accelerate_tpu.utils.memory.time.sleep", lambda _s: None)
    calls = {"n": 0}

    @retry_transient_io
    def broken():
        calls["n"] += 1
        raise FileNotFoundError(2, "No such file")

    with pytest.raises(FileNotFoundError):
        broken()
    assert calls["n"] == 1  # no retry for a real bug


def test_retry_transient_io_gives_up_after_max_attempts(monkeypatch):
    monkeypatch.setattr("accelerate_tpu.utils.memory.time.sleep", lambda _s: None)
    calls = {"n": 0}

    @retry_transient_io(max_attempts=3)
    def always_flaky():
        calls["n"] += 1
        raise OSError(errno.EIO, "Input/output error")

    with pytest.raises(OSError):
        always_flaky()
    assert calls["n"] == 3


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------


def test_should_save_interval_and_preemption(tmp_path):
    acc, model, opt = _make_acc()
    manager = CheckpointManager(
        acc, checkpoint_dir=str(tmp_path), save_interval=5, handle_signals=()
    )
    assert [s for s in range(1, 12) if manager.should_save(s)] == [5, 10]
    manager.request_preemption()
    assert manager.should_save(7)  # preemption overrides the interval
    assert not manager.exit_requested
    manager.save(7)
    assert manager.exit_requested  # boundary save landed → exit cleanly
    assert not manager.should_save(8)  # exactly ONE preemption save


def test_save_rotates_only_after_commit(tmp_path):
    acc, model, opt = _make_acc()
    manager = CheckpointManager(
        acc, checkpoint_dir=str(tmp_path), total_limit=2, handle_signals=()
    )
    batch = jnp.ones((4, 8), jnp.float32)
    for step in (1, 2, 3):
        acc.backward(_loss, batch)
        opt.step()
        opt.zero_grad()
        manager.save(step)
    kept = list_checkpoints(str(tmp_path))
    assert [os.path.basename(p) for p in kept] == ["checkpoint_2", "checkpoint_3"]
    assert verify_checkpoint(kept[-1]) == []


def test_resume_none_modes_and_fresh_run(tmp_path):
    acc, model, opt = _make_acc()
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path), handle_signals=())
    assert manager.resume(None) is None
    assert manager.resume(False) is None
    assert manager.resume("auto") is None  # nothing saved yet: fresh run


def test_resume_explicit_path_refuses_torn_checkpoint(tmp_path):
    acc, model, opt = _make_acc()
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path), handle_signals=())
    manager.save(step=1)
    target = str(tmp_path / "checkpoint_1")
    victim = next(
        os.path.join(target, n) for n in os.listdir(target) if n != "manifest.json"
    )
    os.remove(victim)
    with pytest.raises(ValueError, match="Refusing to resume"):
        manager.resume(target)


def test_resume_restores_step_and_rng_stream(tmp_path):
    from accelerate_tpu.utils.random import next_rng_key, set_seed

    acc, model, opt = _make_acc()
    set_seed(11)
    next_rng_key()  # advance the stream
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path), handle_signals=())
    manager.save(step=4, epoch=1)
    expected_next = np.asarray(jax.random.key_data(next_rng_key()))

    next_rng_key()  # diverge
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc2, model2, opt2 = _make_acc()
    manager2 = CheckpointManager(acc2, checkpoint_dir=str(tmp_path), handle_signals=())
    resume = manager2.resume("auto")
    assert resume.step == 4 and resume.epoch == 1
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(next_rng_key())), expected_next
    )


def test_save_state_atomic_false_keeps_legacy_behavior(tmp_path):
    acc, model, opt = _make_acc()
    acc.save_state(str(tmp_path / "ckpt"), atomic=False)
    assert (tmp_path / "ckpt").exists()
    assert not (tmp_path / "ckpt" / "manifest.json").exists()
    # atomic default writes the manifest
    acc.save_state(str(tmp_path / "ckpt2"))
    assert (tmp_path / "ckpt2" / "manifest.json").exists()
    assert verify_checkpoint(str(tmp_path / "ckpt2")) == []


def test_atomic_resave_same_dir_swaps_cleanly(tmp_path):
    acc, model, opt = _make_acc()
    batch = jnp.ones((4, 8), jnp.float32)
    acc.save_state(str(tmp_path / "ckpt"))
    acc.backward(_loss, batch)
    opt.step()
    opt.zero_grad()
    newer = jax.device_get(model.params)
    acc.save_state(str(tmp_path / "ckpt"))
    assert verify_checkpoint(str(tmp_path / "ckpt")) == []
    assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))
    acc.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(model.params)["w"]), np.asarray(newer["w"])
    )


def test_automatic_naming_rotation_happens_after_commit(tmp_path):
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration

    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True, total_limit=2
        )
    )
    acc.prepare(Tiny())
    acc.prepare_optimizer(optax.sgd(1e-2))
    for _ in range(3):
        acc.save_state()
    kept = list_checkpoints(str(tmp_path / "checkpoints"))
    assert [os.path.basename(p) for p in kept] == ["checkpoint_1", "checkpoint_2"]
    for path in kept:
        assert verify_checkpoint(path) == []


def test_manifest_metadata_records_dataloader_positions(tmp_path):
    acc, model, opt = _make_acc()
    data = [{"x": np.arange(8, dtype=np.float32) + i} for i in range(32)]
    loader = acc.prepare_data_loader(data, batch_size=8, shuffle=True, seed=5)
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path), handle_signals=())
    loader.set_epoch(2)
    it = iter(loader)
    next(it)
    next(it)
    manager.save(step=12, epoch=2)
    meta = read_manifest(str(tmp_path / "checkpoint_12"))["metadata"]
    assert meta["dataloaders"] == [{"epoch": 2, "position": 2}]
    assert meta["sharded"] is False


def test_positions_track_live_loader_after_resumed_epoch(tmp_path):
    """A save in the epoch AFTER a mid-epoch resume must record the live
    loader's epoch/position, not the resumed epoch's skip-wrapper."""
    from accelerate_tpu.fault_tolerance import ResumePoint

    acc, model, opt = _make_acc()
    data = [{"x": np.arange(8, dtype=np.float32) + i} for i in range(32)]
    loader = acc.prepare_data_loader(data, batch_size=8, shuffle=True, seed=5)
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path), handle_signals=())
    resume = ResumePoint(path="x", step=2, epoch=0, dataloaders=[{"epoch": 0, "position": 2}])

    # resumed epoch 0: wrapper in place, positions absolute
    loader.set_epoch(0)
    epoch_loader = manager.resumed_loader(loader, resume, epoch=0)
    assert epoch_loader is not loader
    list(epoch_loader)  # finish the epoch (2 remaining batches)
    manager.save(step=4, epoch=0)
    meta = read_manifest(str(tmp_path / "checkpoint_4"))["metadata"]
    assert meta["dataloaders"] == [{"epoch": 0, "position": 4}]

    # epoch 1: the canonical loop calls resumed_loader again — wrapper undone
    loader.set_epoch(1)
    epoch_loader = manager.resumed_loader(loader, resume, epoch=1)
    assert epoch_loader is loader
    it = iter(epoch_loader)
    next(it)
    manager.save(step=5, epoch=1)
    meta = read_manifest(str(tmp_path / "checkpoint_5"))["metadata"]
    assert meta["dataloaders"] == [{"epoch": 1, "position": 1}]


def test_accelerator_factory_and_save_on_preemption(tmp_path):
    acc, model, opt = _make_acc()
    manager = acc.checkpoint_manager(str(tmp_path), save_interval=10, handle_signals=())
    assert isinstance(manager, CheckpointManager)
    assert manager.save_on_preemption(step=3) is False  # nothing pending: no save
    assert list_checkpoints(str(tmp_path)) == []
    manager.request_preemption()
    assert manager.save_on_preemption(step=3) is True
    assert [os.path.basename(p) for p in list_checkpoints(str(tmp_path))] == ["checkpoint_3"]
    assert manager.save_on_preemption(step=4) is True  # idempotent: still one save
    assert len(list_checkpoints(str(tmp_path))) == 1


def test_manager_rejects_automatic_checkpoint_naming(tmp_path):
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration

    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True
        )
    )
    with pytest.raises(ValueError, match="automatic_checkpoint_naming"):
        CheckpointManager(acc, checkpoint_dir=str(tmp_path), handle_signals=())


def test_preemption_sync_every_gates_the_collective_check(tmp_path):
    acc, model, opt = _make_acc()
    manager = CheckpointManager(
        acc, checkpoint_dir=str(tmp_path), handle_signals=(), preemption_sync_every=4
    )
    manager.request_preemption()
    # only steps on the sync cadence may consult (and act on) the flag —
    # every host evaluates the same gate, keeping the collective aligned
    assert not manager.should_save(3)
    assert not manager.should_save(5)
    assert manager.should_save(4)
    assert manager.should_save(8)


def test_load_state_auto_with_and_without_checksums(tmp_path):
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(project_dir=str(tmp_path))
    acc.prepare(Tiny())
    acc.prepare_optimizer(optax.sgd(1e-2))
    manager = CheckpointManager(acc, handle_signals=())
    assert manager.checkpoint_dir == os.path.join(str(tmp_path), "checkpoints")
    manager.save(step=2)
    acc.load_state("auto")
    acc.load_state("auto", check_checksums=False)
    with pytest.raises(FileNotFoundError, match="auto"):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        acc2 = Accelerator(project_dir=str(tmp_path / "empty"))
        acc2.prepare(Tiny())
        acc2.load_state("auto")


def test_any_process_single_host():
    state = PartialState()
    assert state.any_process(True) is True
    assert state.any_process(False) is False
