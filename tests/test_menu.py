"""Bullet menu (reference commands/menu/ analogue): TTY arrow navigation via
a pty, and the numbered non-TTY fallback the reference lacks."""

import os
import pty
import subprocess
import sys

import pytest

from accelerate_tpu.commands.menu import BulletMenu, select


def test_plain_fallback_default(monkeypatch, capsys):
    monkeypatch.setattr("sys.stdin", type("S", (), {"isatty": lambda self: False})())
    monkeypatch.setattr("builtins.input", lambda prompt="": "")
    assert BulletMenu("pick", ["a", "b", "c"], default=1).run() == 1


def test_plain_fallback_by_index_and_name(monkeypatch):
    monkeypatch.setattr("sys.stdin", type("S", (), {"isatty": lambda self: False})())
    monkeypatch.setattr("builtins.input", lambda prompt="": "2")
    assert BulletMenu("pick", ["a", "b", "c"]).run() == 2
    monkeypatch.setattr("builtins.input", lambda prompt="": "fp16")
    assert select("precision?", ["no", "fp16", "bf16"], "bf16") == "fp16"


def test_plain_fallback_rejects_out_of_range(monkeypatch):
    monkeypatch.setattr("sys.stdin", type("S", (), {"isatty": lambda self: False})())
    monkeypatch.setattr("builtins.input", lambda prompt="": "7")
    with pytest.raises(ValueError, match="out of range"):
        BulletMenu("pick", ["a", "b"]).run()


def _drive_tty(keys: bytes) -> str:
    """Run the menu on a real pty; send keys only once the menu is DRAWN
    (the child's interpreter startup runs in canonical mode — bytes written
    earlier would be cooked, not read by the cbreak loop)."""
    import select as select_mod
    import time

    script = (
        "from accelerate_tpu.commands.menu import BulletMenu;"
        "print('PICKED', BulletMenu('pick', ['no', 'fp16', 'bf16']).run())"
    )
    master, slave = pty.openpty()
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        stdin=slave, stdout=slave, stderr=subprocess.DEVNULL, close_fds=True,
    )
    os.close(slave)
    out = b""
    sent = False
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        ready, _, _ = select_mod.select([master], [], [], 0.2)
        if ready:
            try:
                chunk = os.read(master, 1024)
            except OSError:
                break  # EIO: child exited and released the slave
            if not chunk:
                break
            out += chunk
        if not sent and b"bf16" in out:  # full menu rendered → cbreak active
            time.sleep(0.3)  # let the cbreak tcsetattr land
            os.write(master, keys)
            sent = True
        if proc.poll() is not None and not ready:
            break
    proc.wait(timeout=10)
    os.close(master)
    return out.decode(errors="replace")


def test_tty_arrow_navigation():
    out = _drive_tty(b"\x1b[B\x1b[B\r")  # down, down, enter
    assert "PICKED 2" in out


def test_tty_digit_jump_and_wraparound():
    out = _drive_tty(b"\x1b[A\r")  # up from 0 wraps to last
    assert "PICKED 2" in out
    out = _drive_tty(b"1\r")
    assert "PICKED 1" in out


def test_tty_eof_raises_instead_of_spinning():
    """A hung-up pty must raise EOFError, not busy-loop in cbreak."""
    import time

    script = (
        "from accelerate_tpu.commands.menu import BulletMenu\n"
        "try:\n"
        "    BulletMenu('pick', ['a', 'b']).run()\n"
        "except EOFError:\n"
        "    print('EOF-OK')\n"
    )
    master, slave = pty.openpty()
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        stdin=slave, stdout=slave, stderr=subprocess.DEVNULL, close_fds=True,
    )
    os.close(slave)
    out = b""
    deadline = time.monotonic() + 60
    closed = False
    import select as select_mod

    while time.monotonic() < deadline:
        ready, _, _ = select_mod.select([master], [], [], 0.2)
        if ready:
            try:
                chunk = os.read(master, 1024)
            except OSError:
                break
            if not chunk:
                break
            out += chunk
        if not closed and b"b\r\n" in out:  # menu drawn → now hang up stdin
            time.sleep(0.3)
            os.write(master, b"\x04")  # cbreak: VEOF delivers a 0-byte read
            closed = True
        if proc.poll() is not None and not ready:
            break
    proc.wait(timeout=10)
    os.close(master)
    assert b"EOF-OK" in out, out


def test_ss3_arrows_navigate():
    out = _drive_tty(b"\x1bOB\r")  # SS3 down
    assert "PICKED 1" in out
