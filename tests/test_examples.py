"""Every example must run green on the virtual mesh (reference
tests/test_examples.py:41-43 — tiny bundled data, subprocess execution)."""

import os
import re
import subprocess
import sys

import pytest

EXAMPLES_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def run_example(path, *args, timeout=420):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    result = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES_DIR, path), *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )
    assert result.returncode == 0, f"{path} failed:\n{result.stdout}\n{result.stderr}"
    return result.stdout


def test_nlp_example():
    out = run_example("nlp_example.py", "--num_epochs", "1")
    assert re.search(r"epoch 0: \{'accuracy': [\d.]+, 'f1': [\d.]+\}", out)


def test_gradient_accumulation_example():
    out = run_example("by_feature/gradient_accumulation.py", "--num_epochs", "1")
    # 48 samples / batch 8 = 6 batches with a 4-batch window → one full window
    # plus the end-of-epoch partial sync = exactly 2 optimizer steps
    assert "optimizer_steps=2" in out
    assert "fused accumulation step" in out


def test_checkpointing_example_resume(tmp_path):
    out = run_example(
        "by_feature/checkpointing.py", "--checkpoint_dir", str(tmp_path), "--num_epochs", "1"
    )
    assert "saved epoch_0" in out
    assert os.path.exists(tmp_path / "epoch_0" / "model_0.safetensors")
    out = run_example(
        "by_feature/checkpointing.py",
        "--checkpoint_dir", str(tmp_path),
        "--num_epochs", "2",
        "--resume_from_checkpoint", "epoch_0",
    )
    assert "resumed from epoch_0 at epoch 1" in out
    assert "saved epoch_1" in out


def test_telemetry_example(tmp_path):
    import json

    # sample_every=2 so the post-resume phase (6 steps) completes ≥2 sampling
    # windows and the percentile fields are populated
    out = run_example(
        "by_feature/telemetry.py",
        "--project_dir", str(tmp_path), "--num_steps", "12", "--sample_every", "2",
    )
    assert "Telemetry demo complete" in out
    assert re.search(r"goodput [\d.]+ after 1 restart", out)
    records = [json.loads(l) for l in (tmp_path / "telemetry.jsonl").read_text().splitlines()]
    metrics = records[-1]["metrics"]
    for key in ("step_time_p50_ms", "tokens_per_sec", "mfu", "compile_count", "goodput"):
        assert key in metrics, sorted(metrics)
    assert records[-1]["goodput"]["restarts"] == 1


def test_analysis_example(tmp_path):
    import json

    out = run_example("by_feature/analysis.py", "--project_dir", str(tmp_path))
    assert "analysis demo complete" in out
    assert "donation: 76/76 declared buffers aliased" in out
    assert "HOST_SYNC" in out and "WARM_RECOMPILE" in out
    records = [json.loads(l) for l in (tmp_path / "telemetry.jsonl").read_text().splitlines()]
    kinds = {r["kind"] for r in records}
    assert "analysis" in kinds  # the audit report + the sanitizer summary


def test_tracking_example(tmp_path):
    import json

    out = run_example("by_feature/tracking.py", "--project_dir", str(tmp_path), "--num_epochs", "1")
    assert re.search(r"epoch 0: \{'accuracy': [\d.]+", out)
    metrics_file = tmp_path / "nlp_example" / "metrics.jsonl"
    assert metrics_file.exists()
    lines = [json.loads(l) for l in metrics_file.read_text().splitlines()]
    assert lines[0]["_config"]["num_epochs"] == 1
    assert any("train_loss" in l for l in lines)
    assert any("accuracy" in l for l in lines)


def test_local_sgd_example():
    out = run_example("by_feature/local_sgd.py", "--num_epochs", "1")
    assert re.search(r"final: \{'accuracy'", out)


def test_memory_example():
    out = run_example("by_feature/memory.py", "--starting_batch_size", "16")
    assert "executable batch size: 16" in out


def test_early_stopping_example():
    out = run_example("by_feature/early_stopping.py", "--num_epochs", "2", "--threshold", "10.0")
    # threshold 10: triggers immediately on the first step
    assert "early stopping engaged" in out


def test_multi_process_metrics_example():
    out = run_example("by_feature/multi_process_metrics.py")
    assert "exact sample count: 48 == 48" in out


def test_complete_nlp_example(tmp_path):
    out = run_example(
        "complete_nlp_example.py", "--num_epochs", "1", "--with_tracking",
        "--checkpointing_steps", "epoch", "--output_dir", str(tmp_path),
    )
    assert re.search(r"epoch 0: \{'accuracy'", out)
    assert os.path.exists(tmp_path / "epoch_0" / "model_0.safetensors")
    assert os.path.exists(tmp_path / "complete_nlp_example" / "metrics.jsonl")
    # resume from the epoch checkpoint
    out = run_example(
        "complete_nlp_example.py", "--num_epochs", "2",
        "--resume_from_checkpoint", str(tmp_path / "epoch_0"), "--output_dir", str(tmp_path),
    )
    assert "resumed at epoch 1" in out
    assert re.search(r"epoch 1: \{'accuracy'", out)


def test_cv_example():
    out = run_example("cv_example.py", "--num_epochs", "4")
    match = re.search(r"epoch 3: loss=[\d.]+ accuracy=([\d.]+)", out)
    assert match, out
    assert float(match.group(1)) > 0.5  # a convnet must beat 3-way chance solidly


def test_schedule_free_example():
    out = run_example("by_feature/schedule_free.py", "--num_epochs", "1")
    assert re.search(r"epoch 0: loss=[\d.]+ \{'accuracy'", out)


def test_automatic_gradient_accumulation_example():
    out = run_example("by_feature/automatic_gradient_accumulation.py", "--observed_batch_size", "32")
    assert re.search(r"final: batch_size=\d+ accumulation=\d+", out)


def test_cross_validation_example():
    out = run_example("by_feature/cross_validation.py", "--num_folds", "2")
    assert "fold 1:" in out
    assert re.search(r"mean accuracy over 2 folds: [\d.]+", out)


def test_complete_cv_example(tmp_path):
    out = run_example(
        "complete_cv_example.py", "--num_epochs", "1", "--with_tracking",
        "--checkpointing_steps", "epoch", "--output_dir", str(tmp_path),
    )
    assert re.search(r"epoch 0: accuracy=[\d.]+", out)
    assert os.path.exists(tmp_path / "epoch_0" / "model_0.safetensors")
    out = run_example(
        "complete_cv_example.py", "--num_epochs", "2",
        "--resume_from_checkpoint", str(tmp_path / "epoch_0"), "--output_dir", str(tmp_path),
    )
    assert "resumed at epoch 1" in out
    assert re.search(r"epoch 1: accuracy=[\d.]+", out)


def test_fsdp_with_peak_mem_tracking_example():
    out = run_example("by_feature/fsdp_with_peak_mem_tracking.py", "--num_epochs", "1")
    assert re.search(r"epoch 0: (peak HBM|host RSS) [\d.]+ MiB", out)
    assert re.search(r"epoch 0: \{'accuracy'", out)


def test_big_model_inference_example(tmp_path):
    out = run_example(
        "inference/big_model_inference.py", "--model", "llama-tiny",
        "--ckpt", str(tmp_path / "ckpt"), "--placement", "cpu", "--max_new_tokens", "4",
    )
    assert re.search(r"generation: [\d.]+ s/token", out)
    assert "tokens:" in out


def test_big_model_inference_example_gpt2(tmp_path):
    out = run_example(
        "inference/big_model_inference.py", "--model", "gpt2-tiny",
        "--ckpt", str(tmp_path / "ckpt"), "--placement", "cpu", "--max_new_tokens", "4",
    )
    assert re.search(r"generation: [\d.]+ s/token", out)
    assert "tokens:" in out


@pytest.mark.parametrize(
    "script,args",
    [
        ("inference/llama.py", ["--model", "llama-tiny", "--tensor", "2", "--max_new_tokens", "4"]),
        ("inference/gpt2.py", ["--model", "gpt2-tiny", "--tensor", "2", "--max_new_tokens", "4"]),
        ("inference/bert.py", ["--model", "bert-tiny", "--tensor", "2"]),
        ("inference/t5.py", ["--model", "t5-tiny", "--tensor", "2", "--max_new_tokens", "4"]),
    ],
)
def test_per_model_inference_examples(script, args):
    """Per-family walkthroughs (reference examples/inference/{bert,gpt2,llama,t5}.py)."""
    out = run_example(script, *args)
    assert "ok" in out


def test_distributed_inference_example():
    out = run_example("inference/distributed_inference.py", "--max_new_tokens", "4")
    assert re.search(r"process\(es\) generated 5 sequences", out)
    # one generation per prompt, each echoing its prompt prefix
    assert out.count("[1, 2, 3,") == 1 and out.count("[13, 14, 15,") == 1
