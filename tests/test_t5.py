"""T5 encoder-decoder family: training, TP parity, streaming, decode parity.

VERDICT r3 #3: encoder-decoder coverage (reference examples/inference/t5.py,
T0pp row of benchmarks/README.md:35).
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, ParallelismConfig, dispatch_model
from accelerate_tpu.models import T5, build_model


def _model_and_params(seed=0):
    model = T5("t5-tiny")
    params = model.init(jax.random.key(seed))
    return model, params


def _batch(seed=0, b=4, s_enc=16, s_dec=12):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": jnp.asarray(rng.integers(0, 1024, (b, s_enc)), jnp.int32),
        "labels": jnp.asarray(rng.integers(0, 1024, (b, s_dec)), jnp.int32),
    }


def test_build_model_registry():
    model = build_model("t5-tiny")
    assert model.is_encoder_decoder
    assert model.config.arch == "t5"


def test_shift_right():
    model, _ = _model_and_params()
    labels = jnp.asarray([[5, 6, 7]], jnp.int32)
    shifted = model.shift_right(labels)
    np.testing.assert_array_equal(np.asarray(shifted), [[0, 5, 6]])


def test_t5_trains():
    accelerator = Accelerator()
    model = T5("t5-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    loss_fn = T5.loss_fn(model)
    batch = _batch()
    losses = []
    for _ in range(8):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_t5_tp_forward_matches_single_device():
    model, params = _model_and_params(seed=1)
    batch = _batch(seed=1)
    dec = model.shift_right(batch["labels"])
    expected = model.apply(params, batch["input_ids"], dec)

    accelerator = Accelerator(parallelism=ParallelismConfig(tensor=2, fsdp=2))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(batch["input_ids"], dec)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_t5_masked_loss_matches_manual():
    """Padding on both sides (encoder + decoder) flows through the masks."""
    model, params = _model_and_params(seed=2)
    rng = np.random.default_rng(2)
    batch = {
        "input_ids": jnp.asarray(rng.integers(0, 1024, (2, 10)), jnp.int32),
        "labels": jnp.asarray(rng.integers(0, 1024, (2, 6)), jnp.int32),
        "attention_mask": jnp.asarray([[1] * 10, [1] * 7 + [0] * 3], jnp.int32),
        "decoder_attention_mask": jnp.asarray([[1] * 6, [1] * 4 + [0] * 2], jnp.int32),
    }
    loss = T5.loss_fn(model)(params, batch)
    assert np.isfinite(float(loss))
    # padded encoder tokens must not influence the unpadded rows' logits
    dec = model.shift_right(batch["labels"])
    full = model.apply(params, batch["input_ids"], dec, batch["attention_mask"])
    trunc = model.apply(params, batch["input_ids"][1:, :7], dec[1:])
    np.testing.assert_allclose(
        np.asarray(full[1]), np.asarray(trunc[0]), atol=2e-4
    )


def test_t5_streamed_call_matches_apply():
    """Full-sequence streamed forward (decoder stack streamed from host RAM)
    == the plain apply."""
    model, params = _model_and_params(seed=3)
    batch = _batch(seed=3, b=2)
    dec = model.shift_right(batch["labels"])
    expected = model.apply(params, batch["input_ids"], dec)

    from accelerate_tpu.big_modeling import make_layered_device_map

    lm = dispatch_model(
        model, params, device_map=make_layered_device_map(model, "cpu"), dtype=jnp.float32
    )
    got = lm(batch["input_ids"], dec)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-3)


def test_t5_streamed_generate_matches_full_recompute():
    """Greedy streamed KV-cache decode == argmax over full re-applies."""
    model, params = _model_and_params(seed=4)
    rng = np.random.default_rng(4)
    enc_ids = jnp.asarray(rng.integers(0, 1024, (2, 12)), jnp.int32)
    n_new = 6

    # reference: full recompute greedy decode
    dec = jnp.zeros((2, 1), jnp.int32)  # decoder_start_token_id = 0
    for _ in range(n_new):
        logits = model.apply(params, enc_ids, dec)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        dec = jnp.concatenate([dec, nxt[:, None]], axis=1)

    from accelerate_tpu.big_modeling import Seq2SeqStreamedModel, make_layered_device_map

    lm = dispatch_model(
        model, params, device_map=make_layered_device_map(model, "cpu"), dtype=jnp.float32
    )

    assert isinstance(lm, Seq2SeqStreamedModel)
    got = lm.generate(enc_ids, max_new_tokens=n_new)
    np.testing.assert_array_equal(np.asarray(dec), got)


def test_t5_streamed_generate_with_encoder_mask():
    """Padded encoder inputs give the same generation as the truncated ones."""
    model, params = _model_and_params(seed=5)
    rng = np.random.default_rng(5)
    real = jnp.asarray(rng.integers(1, 1024, (1, 9)), jnp.int32)
    padded = jnp.concatenate([real, jnp.zeros((1, 3), jnp.int32)], axis=1)
    am = jnp.asarray([[1] * 9 + [0] * 3], jnp.int32)

    from accelerate_tpu.big_modeling import make_layered_device_map

    lm = dispatch_model(
        model, params, device_map=make_layered_device_map(model, "cpu"), dtype=jnp.float32
    )
    out_padded = lm.generate(padded, max_new_tokens=5, attention_mask=am)
    out_real = lm.generate(real, max_new_tokens=5)
    np.testing.assert_array_equal(out_padded, out_real)


def test_t5_remat_matches():
    """Activation checkpointing must not change the math."""
    from accelerate_tpu import FullyShardedDataParallelPlugin

    model, params = _model_and_params(seed=6)
    batch = _batch(seed=6)
    dec = model.shift_right(batch["labels"])
    expected = model.apply(params, batch["input_ids"], dec)

    accelerator = Accelerator(
        fsdp_plugin=FullyShardedDataParallelPlugin(stage=3, activation_checkpointing=True)
    )
    prepared = accelerator.prepare_model(model, params=params)
    assert model.remat_layers
    got = prepared(batch["input_ids"], dec)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_t5_pipeline_forward_matches_single_device():
    """Both stacks pipeline over the mesh axis: encoder schedule first, then
    the decoder schedule with enc_out as a per-microbatch side input."""
    model, params = _model_and_params(seed=7)
    batch = _batch(seed=7, b=8)
    dec = model.shift_right(batch["labels"])
    expected = model.apply(params, batch["input_ids"], dec)
    model.pipeline_fn = model.enc_pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    assert model.pipeline_fn is not None and model.enc_pipeline_fn is not None
    assert prepared.params["layers"]["self_wq"].sharding.spec[0] == "pipeline"
    assert prepared.params["encoder"]["wq"].sharding.spec[0] == "pipeline"
    got = prepared(batch["input_ids"], dec)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_t5_pipeline_with_masks_matches():
    model, params = _model_and_params(seed=8)
    rng = np.random.default_rng(8)
    enc_ids = jnp.asarray(rng.integers(0, 1024, (8, 12)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 1024, (8, 8)), jnp.int32)
    am = np.ones((8, 12), np.int32); am[0, 9:] = 0
    dm = np.ones((8, 8), np.int32); dm[1, 5:] = 0
    am, dm = jnp.asarray(am), jnp.asarray(dm)
    dec = model.shift_right(labels)
    expected = model.apply(params, enc_ids, dec, am, dm)
    model.pipeline_fn = model.enc_pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(enc_ids, dec, am, dm)
    real = np.asarray(dm, bool)
    np.testing.assert_allclose(np.asarray(expected)[real], np.asarray(got)[real], atol=2e-4)


def test_t5_pipeline_trains():
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, data=4))
    model = T5("t5-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    loss_fn = T5.loss_fn(model)
    batch = _batch(seed=9, b=8)
    losses = []
    for _ in range(6):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_t5_streamed_ignores_stale_pipeline_hook():
    """A mesh-bound enc_pipeline_fn left on the model must not be traced into
    the single-device streaming executor (ADVICE r4: mirror Bert's
    use_attention_hook=False pattern)."""
    model, params = _model_and_params(seed=10)
    params = jax.device_get(params)
    batch = _batch(seed=10, b=2)
    dec = model.shift_right(batch["labels"])
    expected = np.asarray(model.apply(params, batch["input_ids"], dec))

    Accelerator(parallelism=ParallelismConfig(pipeline=2)).prepare_model(model, params=params)
    assert model.enc_pipeline_fn is not None  # stale hook installed
    from accelerate_tpu.big_modeling import make_layered_device_map

    lm = dispatch_model(
        model, params, device_map=make_layered_device_map(model, "cpu"), dtype=jnp.float32
    )
    got = np.asarray(lm(batch["input_ids"], dec))
    np.testing.assert_allclose(expected, got, atol=2e-3)
    out = lm.generate(batch["input_ids"], max_new_tokens=3)
    assert out.shape == (2, 4)
