"""Speculative decoding: draft-model propose, one-step paged verify, COW
tree branches (serving/speculative.py + the engine's _spec_* step path).

The acceptance bar is bit-equality: at temperature 0 a speculative engine
must emit EXACTLY the tokens the plain engine emits — the draft model can
change how many tokens land per step, never which tokens. Every leg here
(kernel and reference verify paths, gpt2 and llama-GQA protocols, chunked
prefill, tree branches, a mid-stream chaos disable, a disagg handoff of a
speculating slot) is gated on that equality, with the zero-steady-state-
recompile and exact-accounting invariants pinned alongside.
"""

import json

import numpy as np
import pytest

import jax

from accelerate_tpu.models import GPT2, Llama
from accelerate_tpu.resilience import FaultPlan
from accelerate_tpu.serving import ServingEngine, SpeculativeConfig, run_offered_load
from accelerate_tpu.telemetry import (
    RequestTracer,
    ServingStats,
    Telemetry,
    TelemetryConfig,
    fleet_rollup,
)


@pytest.fixture(scope="module")
def llama():
    model = Llama("llama-tiny")
    return model, model.init(jax.random.key(0))


@pytest.fixture(scope="module")
def gpt2():
    model = GPT2("gpt2-tiny")
    return model, model.init(jax.random.key(1))


def _prompts(lengths, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (s,)).astype(np.int32) for s in lengths]


def _shrunk_draft(model, seed=7):
    """A genuinely different (randomly initialized, shallower) draft from
    the same family — the realistic shape: low acceptance, but the verify
    step must keep the output stream the target's own."""
    draft = type(model)(model.config.replace(num_layers=max(1, model.config.num_layers // 2)))
    return draft, draft.init(jax.random.key(seed))


def _engines(model, params, spec_cfg, **kw):
    """A (plain, speculative) engine pair over identical geometry."""
    kwargs = dict(num_slots=2, max_len=64, page_size=8)
    kwargs.update(kw)
    plain = ServingEngine(model, params, **kwargs)
    spec = ServingEngine(model, params, speculative=spec_cfg, **kwargs)
    return plain, spec


def _assert_equal_outputs(base, outs):
    assert len(base) == len(outs)
    for i, (b, o) in enumerate(zip(base, outs)):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(o), err_msg=f"request {i}")


# -- config validation --------------------------------------------------------


def test_speculative_config_validation(llama):
    model, params = llama
    with pytest.raises(ValueError, match="k must be >= 1"):
        SpeculativeConfig(draft_model=model, draft_params=params, k=0)
    with pytest.raises(ValueError, match="mode"):
        SpeculativeConfig(draft_model=model, draft_params=params, mode="dag")
    with pytest.raises(ValueError, match="num_branches"):
        SpeculativeConfig(draft_model=model, draft_params=params, mode="tree", num_branches=1)
    cfg = SpeculativeConfig(draft_model=model, draft_params=params, k=3)
    with pytest.raises(ValueError, match="paged"):
        ServingEngine(model, params, num_slots=2, max_len=64, paged=False, speculative=cfg)
    with pytest.raises(ValueError, match="temperature-0"):
        ServingEngine(model, params, num_slots=2, max_len=64, temperature=0.7, speculative=cfg)
    bad_draft = Llama(model.config.replace(vocab_size=512))
    bad = SpeculativeConfig(
        draft_model=bad_draft, draft_params=bad_draft.init(jax.random.key(2))
    )
    with pytest.raises(ValueError, match="vocab_size"):
        ServingEngine(model, params, num_slots=2, max_len=64, speculative=bad)


# -- temp-0 bit-equality: both protocols, both verify paths -------------------


@pytest.mark.parametrize("use_kernels", [False, True], ids=["reference", "kernel"])
@pytest.mark.parametrize("family", ["llama", "gpt2"])
def test_linear_token_equality(family, use_kernels, llama, gpt2):
    """Speculative linear mode == plain decode, token-bit-equal, for the
    GQA protocol (llama: 4 q heads on 2 kv heads) and the MHA+tied-embedding
    protocol (gpt2), on BOTH verify implementations (the windowed paged
    kernel and the _gathered_view reference)."""
    model, params = llama if family == "llama" else gpt2
    draft, draft_params = _shrunk_draft(model)
    cfg = SpeculativeConfig(draft_model=draft, draft_params=draft_params, k=3)
    kw = dict(page_size=16, max_len=96) if use_kernels else {}
    plain, spec = _engines(model, params, cfg, use_kernels=use_kernels, **kw)
    if use_kernels:
        assert spec._use_decode_kernel, spec._kernel_fallback_reason
    prompts = _prompts([3, 7, 12, 17], seed=3)
    base = plain.generate_many(prompts, max_new_tokens=6)
    outs = spec.generate_many(prompts, max_new_tokens=6)
    _assert_equal_outputs(base, outs)


@pytest.mark.parametrize("use_kernels", [False, True], ids=["reference", "kernel"])
def test_tree_token_equality(llama, use_kernels):
    """Tree mode (2 COW-forked branches off the draft's top-2 first tokens)
    commits the winning branch only — same bit-equality bar."""
    model, params = llama
    draft, draft_params = _shrunk_draft(model)
    cfg = SpeculativeConfig(
        draft_model=draft, draft_params=draft_params, k=3, mode="tree", num_branches=2
    )
    kw = dict(page_size=16, max_len=96) if use_kernels else {}
    # prefix_sharing off so the drained allocator must read exactly 0 —
    # branch forks borrow and return pages, never leak them
    plain, spec = _engines(model, params, cfg, use_kernels=use_kernels,
                           prefix_sharing=False, **kw)
    prompts = _prompts([3, 9, 14], seed=5)
    base = plain.generate_many(prompts, max_new_tokens=6)
    outs = spec.generate_many(prompts, max_new_tokens=6)
    _assert_equal_outputs(base, outs)
    assert spec.cache.pages.used_count == 0


def test_chunked_prefill_token_equality(llama):
    """Chunked prefill mirrors every span into the draft pool chunk by
    chunk, so a long prompt admitted across several steps drafts from
    complete draft K/V — and stays bit-equal."""
    model, params = llama
    draft, draft_params = _shrunk_draft(model)
    cfg = SpeculativeConfig(draft_model=draft, draft_params=draft_params, k=3)
    plain, spec = _engines(model, params, cfg, prefill_chunk=16)
    prompts = _prompts([40, 5, 23], seed=11)
    base = plain.generate_many(prompts, max_new_tokens=6)
    outs = spec.generate_many(prompts, max_new_tokens=6)
    _assert_equal_outputs(base, outs)


# -- acceptance + the compile invariant ---------------------------------------


@pytest.mark.parametrize("mode", ["linear", "tree"])
def test_self_draft_acceptance_and_zero_steady_compiles(llama, mode):
    """With the TARGET as its own draft (the oracle: every candidate is the
    target's argmax) acceptance saturates at k-1 extra tokens per drafting
    step — and after warmup() NOTHING compiles mid-traffic in either mode."""
    _, params = llama
    model = Llama("llama-tiny")  # fresh jit cache: compile counts are exact
    k = 3
    cfg = SpeculativeConfig(
        draft_model=model, draft_params=params, k=k, mode=mode,
        num_branches=2,
    )
    plain, spec = _engines(model, params, cfg, prefix_sharing=False)
    spec.warmup()
    warm = spec.compiles.compile_count
    prompts = _prompts([3, 7, 12, 5], seed=9)
    base = plain.generate_many(prompts, max_new_tokens=8)
    outs = spec.generate_many(prompts, max_new_tokens=8)
    assert spec.compiles.compile_count == warm, spec.compiles.recent_miss_keys
    _assert_equal_outputs(base, outs)
    stats = spec.stats
    assert stats.spec_steps > 0
    assert stats.spec_accepted_tokens > 0
    assert stats.spec_proposed_tokens >= stats.spec_accepted_tokens
    # the oracle's steady-state accepted length is exactly k-1 extras
    # (shorter only on an EOS/budget-capped final window)
    assert max(stats.spec_accepted_lengths) == k - 1
    snap = stats.snapshot()
    assert snap["spec_accepted_len_p50"] == float(k - 1)
    # pages fully released after drain
    assert spec.cache.pages.used_count == 0
    # slot reuse: stale draft tracking from retired requests re-seeds on
    # admit — a second wave over the same lanes stays bit-equal and compiles
    # nothing
    wave2 = _prompts([6, 11, 4], seed=10)
    base2 = plain.generate_many(wave2, max_new_tokens=6)
    outs2 = spec.generate_many(wave2, max_new_tokens=6)
    assert spec.compiles.compile_count == warm, spec.compiles.recent_miss_keys
    _assert_equal_outputs(base2, outs2)


def test_shrunk_draft_still_counts_proposals(llama):
    """A random draft proposes k per drafting step and accepts ~0 — the
    counters stay exact (offered == terminated, proposed >= accepted)."""
    model, params = llama
    draft, draft_params = _shrunk_draft(model)
    cfg = SpeculativeConfig(draft_model=draft, draft_params=draft_params, k=4)
    engine = ServingEngine(model, params, num_slots=2, max_len=64, page_size=8,
                           speculative=cfg)
    engine.generate_many(_prompts([3, 6], seed=21), max_new_tokens=5)
    stats = engine.stats
    assert stats.spec_steps > 0
    assert stats.spec_proposed_tokens > 0
    assert stats.spec_accepted_tokens <= stats.spec_proposed_tokens
    assert all(0 <= a < cfg.k for a in stats.spec_accepted_lengths)


# -- chaos: mid-stream disable ------------------------------------------------


def test_chaos_mid_stream_disable_no_drop_no_dup(llama):
    """FaultPlan(spec_disable_step=N) kills drafting mid-stream; the plain
    decode program takes over from the SAME pending/length state — the
    emitted stream crosses the boundary without a dropped or duplicated
    token, and the fallback is accounted."""
    model, params = llama
    cfg = SpeculativeConfig(draft_model=model, draft_params=params, k=3)
    kwargs = dict(num_slots=2, max_len=64, page_size=8)
    plain = ServingEngine(model, params, **kwargs)
    spec = ServingEngine(model, params, speculative=cfg,
                         fault_plan=FaultPlan(spec_disable_step=3), **kwargs)
    prompts = _prompts([3, 7], seed=13)
    base = plain.generate_many(prompts, max_new_tokens=10)
    outs = spec.generate_many(prompts, max_new_tokens=10)
    _assert_equal_outputs(base, outs)
    assert spec.spec.enabled is False
    assert spec.spec.disabled_reason == "chaos"
    assert spec.stats.spec_fallbacks == 1
    # speculation ran before the drill hit, then stopped for good
    assert spec.stats.spec_steps > 0
    assert spec.stats.requests_completed == len(prompts)


def test_chaos_spec_disable_env_knob(monkeypatch):
    """The drill is reachable from the operator surface: the env var parses
    into the plan and fires exactly once at the named step."""
    monkeypatch.setenv("ACCELERATE_CHAOS_SPEC_DISABLE_STEP", "2")
    plan = FaultPlan.from_env()
    assert plan is not None and plan.spec_disable_step == 2
    assert plan.active
    assert not plan.spec_disable(1)
    assert plan.spec_disable(2)


# -- disagg: handoff of a speculating slot ------------------------------------


def test_handoff_adopted_slot_resumes_speculating(llama):
    """Prefill on a source engine, adopt the live KV on a speculating
    destination: the adopted slot catches the draft pool up by mirrored
    prefill spans and then DRAFTS — tokens bit-equal plain decode, with
    accepted tokens recorded on the destination."""
    model, params = llama
    prompt = _prompts([19], seed=17)[0]
    max_new = 8
    kwargs = dict(num_slots=2, max_len=64, page_size=8, prefix_sharing=False)
    plain = ServingEngine(model, params, **kwargs)
    base = plain.generate_many([prompt], max_new_tokens=max_new)[0]

    src = ServingEngine(model, params, **kwargs)
    cfg = SpeculativeConfig(draft_model=model, draft_params=params, k=3)
    dst = ServingEngine(model, params, speculative=cfg, **kwargs)
    rid = src.submit(prompt, max_new_tokens=max_new, prefill_only=True)
    src.run()
    layout = src.kv_page_layout(rid)
    assert layout is not None
    kb, vb = src.extract_pages(layout["pages"])
    dst_rid = dst.adopt_kv(prompt, max_new, layout, kb, vb, request_id=rid)
    assert src.release_parked(rid)
    result = dst.run()[dst_rid]
    np.testing.assert_array_equal(np.asarray(base)[-max_new:], np.asarray(result.generated))
    # the adopted slot really speculated (oracle draft: acceptance > 0)
    assert dst.stats.spec_accepted_tokens > 0
    assert dst.cache.pages.used_count == 0


# -- loadgen accounting -------------------------------------------------------


def test_offered_load_accounting_exact(llama):
    """run_offered_load over a speculative engine: every offered request
    terminates, token accounting exact — multi-token commits never
    over- or under-run a request's budget."""
    model, params = llama
    cfg = SpeculativeConfig(draft_model=model, draft_params=params, k=3)
    engine = ServingEngine(model, params, num_slots=2, max_len=64, page_size=8,
                           speculative=cfg)
    prompts = _prompts([3, 5, 8, 4], seed=19)
    point = run_offered_load(engine, prompts, 6, offered_rps=200.0)
    assert point["offered_requests"] == len(prompts)
    assert point["requests_completed"] == len(prompts)
    assert point["tokens_generated"] == len(prompts) * 6
    assert point["compile_count"] >= 0  # key present for bench consumers


# -- telemetry: records, spans, rollup ----------------------------------------


def test_speculative_telemetry_records_and_spans(llama, tmp_path):
    """Per-step {"kind": "speculative"} records carry proposed/accepted
    samples; a traced engine opens draft[i] -> verify[i] span pairs; the
    chaos disable lands a terminal record with its fallback_reason."""
    model, params = llama
    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    tracer = RequestTracer(telemetry=hub, sample_every=1)
    cfg = SpeculativeConfig(draft_model=model, draft_params=params, k=3)
    engine = ServingEngine(
        model, params, num_slots=2, max_len=64, page_size=8, speculative=cfg,
        telemetry=hub, tracer=tracer, name="spec0",
        fault_plan=FaultPlan(spec_disable_step=2),
    )
    engine.generate_many(_prompts([3, 7], seed=23), max_new_tokens=8)
    hub.finish(flush=False)
    lines = [json.loads(l) for l in open(tmp_path / "telemetry.jsonl")]
    steps = [r for r in lines if r["kind"] == "speculative" and "proposed_tokens" in r]
    assert steps, "no per-step speculative records"
    for r in steps:
        assert r["engine"] == "spec0"
        assert r["k"] == 3 and r["mode"] == "linear"
        assert r["proposed_tokens"] > 0
        assert all(0 <= a < 3 for a in r["accepted_lengths"])
    disabled = [r for r in lines if r["kind"] == "speculative" and r.get("event") == "disabled"]
    assert len(disabled) == 1 and disabled[0]["fallback_reason"] == "chaos"
    # every trace that decoded while drafting carries paired draft/verify
    span_kinds = {
        s["kind"] for record in tracer.completed for s in record["spans"]
    }
    assert "draft" in span_kinds and "verify" in span_kinds
    for record in tracer.completed:
        drafts = [s for s in record["spans"] if s["kind"] == "draft"]
        verifies = [s for s in record["spans"] if s["kind"] == "verify"]
        assert len(drafts) == len(verifies)
        for s in drafts + verifies:
            assert s["t1"] is not None  # closed, never dangling
    # span durations feed the rollup's raw-sample merge
    assert len(engine.stats.span_seconds["draft"]) > 0
    assert len(engine.stats.span_seconds["verify"]) > 0


def test_stats_snapshot_and_fleet_rollup_merge():
    """Engine-independent: spec counters SUM across replicas and the fleet
    accepted-length percentiles merge over raw samples (token counts — the
    one family of spec keys that must NOT get the ms scaling)."""
    a, b = ServingStats(2), ServingStats(2)
    a.record_spec_step(proposed=6, accepted_lengths=[2, 2])
    a.record_spec_step(proposed=6, accepted_lengths=[2])
    b.record_spec_step(proposed=3, accepted_lengths=[0])
    b.record_spec_fallback()
    snap = a.snapshot()
    assert snap["spec_steps"] == 2
    assert snap["spec_proposed_tokens"] == 12
    assert snap["spec_accepted_tokens"] == 6
    assert snap["spec_accepted_len_p50"] == 2.0  # tokens, not milliseconds
    out = fleet_rollup([a, b], roles=["decode", "decode"])
    assert out["spec_steps"] == 3
    assert out["spec_proposed_tokens"] == 15
    assert out["spec_accepted_tokens"] == 6
    assert out["spec_fallbacks"] == 1
    # merged over ALL raw samples [2, 2, 2, 0], not a mean of per-replica p50s
    assert out["spec_accepted_len_p50"] == 2.0
    assert out["spec_accepted_len_p99"] == 2.0
    # a spec-free replica contributes zeros, not missing keys
    assert ServingStats(2).snapshot()["spec_steps"] == 0
