"""Tests for ops/operations.py (reference: test_utils/scripts/test_ops.py + test_utils.py)."""

from collections import namedtuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import ops
from accelerate_tpu.state import PartialState

Point = namedtuple("Point", ["x", "y"])


def test_recursively_apply_honors_types():
    data = {"a": [np.ones(2), (np.zeros(3), Point(np.ones(1), np.zeros(1)))], "b": "keep"}
    out = ops.recursively_apply(lambda t: t + 1, data)
    assert isinstance(out["a"][1][1], Point)
    assert out["b"] == "keep"
    np.testing.assert_array_equal(out["a"][0], np.full(2, 2.0))


def test_send_to_device_default_sharding():
    batch = {"input_ids": np.arange(32).reshape(8, 4), "mask": np.ones((8, 4))}
    out = ops.send_to_device(batch)
    assert isinstance(out["input_ids"], jax.Array)
    assert len(out["input_ids"].sharding.device_set) == 8


def test_send_to_device_skip_keys():
    batch = {"x": np.ones(4), "meta": np.zeros(2)}
    out = ops.send_to_device(batch, skip_keys="meta")
    assert isinstance(out["x"], jax.Array)
    assert isinstance(out["meta"], np.ndarray)


def test_gather_global_array():
    state = PartialState()
    x = jax.device_put(np.arange(16, dtype=np.float32).reshape(16, 1), state.data_sharding())
    gathered = ops.gather(x)
    np.testing.assert_array_equal(gathered, np.arange(16, dtype=np.float32).reshape(16, 1))


def test_gather_numpy_single_process():
    np.testing.assert_array_equal(ops.gather(np.ones(3)), np.ones(3))


def test_reduce_and_broadcast_single_process():
    x = {"v": np.full((2,), 3.0)}
    np.testing.assert_array_equal(ops.reduce(x, "sum")["v"], np.full((2,), 3.0))
    np.testing.assert_array_equal(ops.broadcast(x)["v"], np.full((2,), 3.0))


def test_pad_input_tensors():
    batch = {"x": np.arange(10).reshape(10, 1)}
    out = ops.pad_input_tensors(batch, batch_size=10, num_processes=4)
    assert out["x"].shape[0] == 12
    assert out["x"][-1, 0] == 9  # repeats the last row


def test_concatenate_trees():
    trees = [{"x": np.ones((2, 3))}, {"x": np.zeros((4, 3))}]
    out = ops.concatenate(trees)
    assert out["x"].shape == (6, 3)


def test_find_batch_size_and_device():
    batch = {"labels": np.zeros(5), "nested": [np.zeros((5, 7))]}
    assert ops.find_batch_size(batch) == 5
    x = jax.device_put(np.ones(2), jax.devices()[1])
    assert ops.find_device({"a": x}) == jax.devices()[1]


def test_get_data_structure_roundtrip():
    data = {"x": np.ones((3, 2), np.float32), "y": [np.zeros(4, np.int32)]}
    structure = ops.get_data_structure(data)
    rebuilt = ops.initialize_tensors(structure)
    assert rebuilt["x"].shape == (3, 2)
    assert rebuilt["y"][0].dtype == np.int32


def test_convert_to_fp32():
    data = {"a": jnp.ones(2, dtype=jnp.bfloat16), "b": np.ones(2, np.int32)}
    out = ops.convert_to_fp32(data)
    assert out["a"].dtype == jnp.float32
    assert out["b"].dtype == np.int32  # non-float untouched


def _bf16_forward(x):
    return x.astype(jnp.bfloat16)


def test_convert_outputs_to_fp32_pickleable():
    import pickle

    fn = ops.convert_outputs_to_fp32(_bf16_forward)
    restored = pickle.loads(pickle.dumps(fn))
    assert restored(jnp.ones(2)).dtype == jnp.float32


def test_listify():
    assert ops.listify({"x": np.arange(3)}) == {"x": [0, 1, 2]}


def test_gather_object_single():
    assert ops.gather_object([1, 2]) == [1, 2]


def test_broadcast_object_list_single():
    objs = ["a", {"b": 1}]
    assert ops.broadcast_object_list(objs) == ["a", {"b": 1}]


def test_pad_across_processes_single_noop():
    x = np.ones((3, 2))
    np.testing.assert_array_equal(ops.pad_across_processes(x), x)
