"""fp8 (e4m3) matmul compute (reference utils/transformer_engine.py:24-72;
SURVEY §2.9 native-dtype mapping). Previously PrecisionType.FP8 silently
meant bf16 — these tests pin down the real semantics."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator
from accelerate_tpu.models import Llama
from accelerate_tpu.ops.fp8 import E4M3_MAX, fp8_dot, quantize_e4m3


def test_quantize_roundtrip_error_bounded():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(64, 32)).astype(np.float32))
    q, scale = quantize_e4m3(x)
    assert q.dtype == jnp.float8_e4m3fn
    back = q.astype(jnp.float32) * scale
    # e4m3 has a 3-bit mantissa → relative error ≤ 2^-4 per element
    np.testing.assert_allclose(np.asarray(back), np.asarray(x), rtol=2**-3, atol=float(scale))


def test_fp8_dot_close_to_exact():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 16, 64)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
    exact = x @ w
    got = fp8_dot(x, w)
    assert got.dtype == exact.dtype
    err = np.abs(np.asarray(got) - np.asarray(exact)).max() / np.abs(np.asarray(exact)).max()
    assert err < 0.05
    # ...but NOT bitwise equal: it really quantized
    assert not np.array_equal(np.asarray(got), np.asarray(exact))


def test_fp8_dot_differentiable():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))
    g = jax.grad(lambda w: fp8_dot(x, w).sum())(w)
    assert np.isfinite(np.asarray(g)).all()
    exact_g = jax.grad(lambda w: (x @ w).sum())(w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(exact_g), rtol=0.1, atol=0.5)


def test_fp8_accelerator_wires_dot_fn_and_trains():
    acc = Accelerator(mixed_precision="fp8")
    model = Llama("llama-tiny")
    prepared = acc.prepare(model)
    from accelerate_tpu.ops.fp8 import fp8_dot as expected_fn

    assert model.dot_fn is expected_fn
    opt = acc.prepare_optimizer(optax.adam(1e-3))
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 1024, (4, 16)), jnp.int32)
    loss_fn = Llama.loss_fn(model)
    losses = []
    for _ in range(6):
        losses.append(float(acc.backward(loss_fn, {"input_ids": ids})))
        opt.step()
        opt.zero_grad()
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_quantize_e4m3_saturates_exactly_at_amax():
    """The per-tensor scale maps the tensor's abs-max onto E4M3_MAX exactly
    (margin 0), so the largest magnitude survives the cast unclipped and
    nothing overflows to inf."""
    x = jnp.asarray([[-3.0, 0.25], [1.5, 12.0]], jnp.float32)
    q, scale = quantize_e4m3(x)
    back = np.asarray(q.astype(jnp.float32))
    assert float(scale) == pytest.approx(12.0 / E4M3_MAX)
    assert np.isfinite(back).all()
    assert np.abs(back).max() == pytest.approx(E4M3_MAX)


def test_quantize_e4m3_margin_headroom():
    """Each margin bit doubles the scale (TE recipe parity): the quantized
    range shrinks by 2^margin, buying overflow headroom for values that
    grow between scale updates."""
    x = jnp.asarray(np.random.default_rng(6).normal(size=(8, 8)).astype(np.float32))
    _, s0 = quantize_e4m3(x, margin=0)
    q1, s1 = quantize_e4m3(x, margin=1)
    assert float(s1) == pytest.approx(2.0 * float(s0))
    assert np.abs(np.asarray(q1.astype(jnp.float32))).max() <= E4M3_MAX / 2 + 1e-3


def test_quantize_e4m3_zero_tensor_no_nan():
    """An all-zero operand exercises the scale floor: no 0/0, quantized
    values and scale both finite."""
    q, scale = quantize_e4m3(jnp.zeros((4, 4), jnp.float32))
    assert np.isfinite(float(scale))
    np.testing.assert_array_equal(np.asarray(q.astype(jnp.float32)), 0.0)
    out = fp8_dot(jnp.zeros((2, 4), jnp.float32), jnp.zeros((4, 3), jnp.float32))
    assert np.isfinite(np.asarray(out)).all()


def test_fp8_dot_output_dtype_follows_x():
    """The hook contract: output rides x's dtype whatever the compute did —
    bf16 activations stay bf16 through a quantized projection."""
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(2, 16)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
    out = fp8_dot(x, w)
    assert out.dtype == jnp.bfloat16 and out.shape == (2, 4)


def test_fp8_output_differs_from_bf16():
    """fp8 must be observably different from the old silent-bf16 behavior."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    ids = jnp.asarray(np.random.default_rng(4).integers(0, 1024, (2, 8)), jnp.int32)
    outs = {}
    for precision in ("bf16", "fp8"):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        from accelerate_tpu.utils import set_seed

        set_seed(0)
        acc = Accelerator(mixed_precision=precision)
        model = Llama("llama-tiny")
        prepared = acc.prepare(model)
        loss_fn = Llama.loss_fn(model)
        outs[precision] = float(jax.jit(lambda p: loss_fn(p, {"input_ids": ids}))(prepared.params))
    assert outs["fp8"] != outs["bf16"]
    assert abs(outs["fp8"] - outs["bf16"]) < 0.5  # same model, small quant shift


def test_fp8_unsupported_model_raises():
    class Plain:
        def init(self, rng):
            del rng
            return {"w": jnp.zeros((4, 4))}

        @staticmethod
        def apply(params, x):
            return x @ params["w"]

    acc = Accelerator(mixed_precision="fp8")
    with pytest.raises(NotImplementedError, match="fp8"):
        acc.prepare(Plain())


def test_fp8_applies_under_pipeline():
    """fp8 must reach the pipeline execution path, not just the layer scan."""
    from accelerate_tpu import ParallelismConfig
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    ids = jnp.asarray(np.random.default_rng(5).integers(0, 1024, (4, 8)), jnp.int32)
    outs = {}
    for precision in ("bf16", "fp8"):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        from accelerate_tpu.utils import set_seed

        set_seed(0)
        acc = Accelerator(mixed_precision=precision, parallelism=ParallelismConfig(pipeline=2))
        model = Llama("llama-tiny")
        prepared = acc.prepare(model)
        assert model.pipeline_fn is not None
        loss_fn = Llama.loss_fn(model)
        outs[precision] = float(jax.jit(lambda p: loss_fn(p, {"input_ids": ids}))(prepared.params))
    assert outs["fp8"] != outs["bf16"]
    assert abs(outs["fp8"] - outs["bf16"]) < 0.5


def test_fp8_recipe_margin_adds_headroom():
    from accelerate_tpu.ops.fp8 import E4M3_MAX, make_fp8_dot, quantize_e4m3
    from accelerate_tpu.utils import FP8RecipeKwargs

    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(8, 32)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(32, 16)).astype(np.float32))
    q0, s0 = quantize_e4m3(x)
    q2, s2 = quantize_e4m3(x, margin=2)
    # margin=2 backs the scale off 4x, leaving 2 headroom bits in the range
    np.testing.assert_allclose(float(s2), float(s0) * 4.0, rtol=1e-6)
    assert float(jnp.abs(q2.astype(jnp.float32)).max()) <= E4M3_MAX / 4 + 1e-6
    # power-of-2 rescaling is rounding-lossless: the dot output is unchanged
    np.testing.assert_array_equal(
        np.asarray(make_fp8_dot(margin=2)(x, w)), np.asarray(make_fp8_dot()(x, w))
    )
    with pytest.raises(ValueError, match="fp8_format"):
        FP8RecipeKwargs(fp8_format="E5M2")
    with pytest.raises(ValueError, match="margin"):
        # negative margin would overflow e4m3's finite range into NaN
        FP8RecipeKwargs(margin=-2)


def test_fp8_recipe_kwargs_handler_wires_margin():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    from accelerate_tpu.utils import FP8RecipeKwargs

    acc = Accelerator(mixed_precision="fp8", kwargs_handlers=[FP8RecipeKwargs(margin=1)])
    model = Llama("llama-tiny")
    acc.prepare(model)
    from accelerate_tpu.ops.fp8 import fp8_dot

    assert model.dot_fn is not fp8_dot  # recipe-built dot, not the default
    ids = jnp.asarray(np.random.default_rng(7).integers(0, 1024, (2, 8)), jnp.int32)
    loss = jax.jit(lambda p: Llama.loss_fn(model)(p, {"input_ids": ids}))(
        acc._models[-1].params
    )
    assert np.isfinite(float(loss))
