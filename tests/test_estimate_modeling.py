"""estimate-memory from checkpoint headers + generic tied-parameter utilities.

VERDICT r3 items #8 (reference commands/estimate.py:215-299 loads any
checkpoint via the meta device) and #5 (utils/modeling.py:606-693 generic
find/retie on arbitrary trees).
"""

import argparse

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.checkpointing import save_model_weights
from accelerate_tpu.commands.estimate import checkpoint_entries, run
from accelerate_tpu.models import Llama, param_count
from accelerate_tpu.utils.modeling import find_tied_parameters, retie_parameters


def _save_ckpt(tmp_path, max_shard_size="10GB"):
    model = Llama("llama-tiny")
    params = jax.device_get(model.init(jax.random.key(0)))
    save_model_weights(params, str(tmp_path), max_shard_size=max_shard_size)
    return model, params


def test_checkpoint_entries_match_params(tmp_path):
    model, params = _save_ckpt(tmp_path)
    entries = checkpoint_entries(str(tmp_path))
    n = sum(int(np.prod(shape)) for shape, _ in entries.values())
    assert n == param_count(model.config)
    assert entries["embed_tokens"][0] == (1024, 128)


def test_checkpoint_entries_sharded_index(tmp_path):
    """Multi-shard checkpoints resolve through the index.json weight map."""
    model, _ = _save_ckpt(tmp_path, max_shard_size=256 << 10)  # force shards
    import os

    assert any(f.endswith(".index.json") for f in os.listdir(tmp_path))
    entries = checkpoint_entries(str(tmp_path))
    n = sum(int(np.prod(shape)) for shape, _ in entries.values())
    assert n == param_count(model.config)


def test_estimate_cli_prints_checkpoint_table(tmp_path, capsys):
    _save_ckpt(tmp_path)
    args = argparse.Namespace(model_name=str(tmp_path), dtypes=["bfloat16"])
    assert run(args) == 0
    out = capsys.readouterr().out
    assert "Checkpoint:" in out and "bfloat16" in out and "Largest tensor:" in out


def test_estimate_cli_registry_name_still_works(capsys):
    args = argparse.Namespace(model_name="llama-tiny", dtypes=["float32"])
    assert run(args) == 0
    assert "parameters" in capsys.readouterr().out


def test_find_tied_parameters_shared_array():
    shared = np.ones((4, 4), np.float32)
    tree = {"embed": {"w": shared}, "head": {"w": shared}, "other": np.zeros((2,))}
    assert find_tied_parameters(tree) == [["embed/w", "head/w"]]


def test_find_tied_parameters_numpy_views():
    base = np.arange(16, dtype=np.float32)
    tree = {"a": base.reshape(4, 4), "b": base.reshape(2, 8)}
    assert find_tied_parameters(tree) == [["a", "b"]]


def test_find_tied_parameters_none_for_distinct():
    tree = {"a": np.ones((2,)), "b": np.ones((2,))}
    assert find_tied_parameters(tree) == []


def test_retie_parameters_restores_sharing():
    """A load that materialized duplicates gets its ties re-established."""
    shared = jnp.ones((3, 3))
    tree = {"embed": {"w": shared}, "head": {"w": shared}}
    groups = find_tied_parameters(tree)
    # simulate a loader writing fresh copies
    loaded = {
        "embed": {"w": jnp.asarray(np.full((3, 3), 2.0))},
        "head": {"w": jnp.asarray(np.full((3, 3), 2.0))},
    }
    assert find_tied_parameters(loaded) == []
    retie_parameters(loaded, groups)
    assert loaded["embed"]["w"] is loaded["head"]["w"]
    assert find_tied_parameters(loaded) == [["embed/w", "head/w"]]


def test_find_tied_parameters_disjoint_slices_not_tied():
    """Disjoint slices of one flat buffer are distinct tensors (review repro)."""
    base = np.arange(16, dtype=np.float32)
    tree = {"a": base[:8], "b": base[8:]}
    assert find_tied_parameters(tree) == []


# ---------------------------------------------------------------------------
# estimate from a HF config.json, no weights (VERDICT r4 missing #1)
# ---------------------------------------------------------------------------

_HF_CONFIGS = {
    # each mirrors a registry entry exactly, in HF field names
    "llama-7b": {
        "model_type": "llama", "vocab_size": 32000, "hidden_size": 4096,
        "intermediate_size": 11008, "num_hidden_layers": 32,
        "num_attention_heads": 32, "max_position_embeddings": 4096,
        "rms_norm_eps": 1e-5,
    },
    "llama-70b": {
        "model_type": "llama", "vocab_size": 32000, "hidden_size": 8192,
        "intermediate_size": 28672, "num_hidden_layers": 80,
        "num_attention_heads": 64, "num_key_value_heads": 8,
        "max_position_embeddings": 4096,
    },
    "gpt2-124m": {
        "model_type": "gpt2", "vocab_size": 50257, "n_embd": 768,
        "n_layer": 12, "n_head": 12, "n_positions": 1024,
    },
    "bert-base": {
        "model_type": "bert", "vocab_size": 30522, "hidden_size": 768,
        "intermediate_size": 3072, "num_hidden_layers": 12,
        "num_attention_heads": 12, "max_position_embeddings": 512,
        "layer_norm_eps": 1e-12,
    },
    "t5-base": {
        "model_type": "t5", "vocab_size": 32128, "d_model": 768,
        "d_ff": 3072, "num_layers": 12, "num_heads": 12, "d_kv": 64,
        "n_positions": 512,
    },
}


@pytest.mark.parametrize("name", sorted(_HF_CONFIGS))
def test_config_json_matches_registry(name, tmp_path):
    """config.json → TransformerConfig gives the registry's exact count."""
    import json

    from accelerate_tpu.models import get_config
    from accelerate_tpu.models.config import config_from_hf_json

    path = tmp_path / "config.json"
    path.write_text(json.dumps(_HF_CONFIGS[name]))
    config = config_from_hf_json(str(path))
    assert param_count(config) == param_count(get_config(name))


def test_config_json_count_matches_real_init(tmp_path):
    """The config-derived count is the true init count (mistral alias too)."""
    import json

    from accelerate_tpu.models.config import config_from_hf_json

    cfg = dict(_HF_CONFIGS["llama-7b"])
    cfg.update(model_type="mistral", hidden_size=128, intermediate_size=352,
               num_hidden_layers=2, num_attention_heads=4,
               num_key_value_heads=2, vocab_size=1024)
    (tmp_path / "config.json").write_text(json.dumps(cfg))
    config = config_from_hf_json(str(tmp_path))
    model = Llama(config)
    params = jax.eval_shape(model.init, jax.random.key(0))
    n = sum(int(np.prod(leaf.shape)) for leaf in jax.tree.leaves(params))
    assert n == param_count(config)


def test_estimate_cli_from_config_json(tmp_path, capsys):
    """Directory with config.json and NO weights → config estimate path."""
    import json

    (tmp_path / "config.json").write_text(json.dumps(_HF_CONFIGS["llama-7b"]))
    args = argparse.Namespace(model_name=str(tmp_path), dtypes=["bfloat16", "int4"])
    assert run(args) == 0
    out = capsys.readouterr().out
    assert "Config:" in out and "6.74B" in out and "int4" in out


def test_estimate_cli_kv_cache_column(capsys):
    """Serve sizing includes the KV cache: the registry path prints the
    2·L·KV·D·S·B estimate and a +kv column driven by --max-seq-len/--batch."""
    from accelerate_tpu.serving import kv_cache_bytes

    args = argparse.Namespace(
        model_name="llama-tiny", dtypes=["bfloat16"], max_seq_len=128, batch=4
    )
    assert run(args) == 0
    out = capsys.readouterr().out
    assert "KV cache (batch=4, seq=128)" in out and "+kv (serve)" in out
    # the printed bf16 figure is the shared serving formula
    from accelerate_tpu.models import get_config

    expected = kv_cache_bytes(get_config("llama-tiny"), 4, 128, 2)
    assert f"{expected / 1024:.2f} KB" in out or f"{expected / (1024 ** 2):.2f} MB" in out


def test_estimate_cli_kv_cache_skipped_without_config(tmp_path, capsys):
    """params=N has no geometry: the KV request is surfaced, not silent."""
    args = argparse.Namespace(
        model_name="params=1000000", dtypes=["bfloat16"], max_seq_len=256, batch=1
    )
    assert run(args) == 0
    out = capsys.readouterr().out
    assert "needs a model config" in out and "+kv (serve)" not in out


def test_estimate_cli_kv_cache_skipped_for_uncovered_arch(capsys):
    """The decoder-only formula must not print a wrong figure for t5 (per-
    stack layers + cross-attention cache) — skip loudly instead."""
    args = argparse.Namespace(
        model_name="t5-base", dtypes=["bfloat16"], max_seq_len=512, batch=8
    )
    assert run(args) == 0
    out = capsys.readouterr().out
    assert "does not cover arch 't5'" in out and "+kv (serve)" not in out


def test_estimate_cli_prefers_weights_over_config(tmp_path, capsys):
    """When real weights sit next to a config.json, headers win (exact for
    the stored dtypes, including quantized checkpoints)."""
    import json

    _save_ckpt(tmp_path)
    (tmp_path / "config.json").write_text(json.dumps(_HF_CONFIGS["llama-7b"]))
    args = argparse.Namespace(model_name=str(tmp_path), dtypes=["bfloat16"])
    assert run(args) == 0
    assert "Checkpoint:" in capsys.readouterr().out
