"""estimate-memory from checkpoint headers + generic tied-parameter utilities.

VERDICT r3 items #8 (reference commands/estimate.py:215-299 loads any
checkpoint via the meta device) and #5 (utils/modeling.py:606-693 generic
find/retie on arbitrary trees).
"""

import argparse

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.checkpointing import save_model_weights
from accelerate_tpu.commands.estimate import checkpoint_entries, run
from accelerate_tpu.models import Llama, param_count
from accelerate_tpu.utils.modeling import find_tied_parameters, retie_parameters


def _save_ckpt(tmp_path, max_shard_size="10GB"):
    model = Llama("llama-tiny")
    params = jax.device_get(model.init(jax.random.key(0)))
    save_model_weights(params, str(tmp_path), max_shard_size=max_shard_size)
    return model, params


def test_checkpoint_entries_match_params(tmp_path):
    model, params = _save_ckpt(tmp_path)
    entries = checkpoint_entries(str(tmp_path))
    n = sum(int(np.prod(shape)) for shape, _ in entries.values())
    assert n == param_count(model.config)
    assert entries["embed_tokens"][0] == (1024, 128)


def test_checkpoint_entries_sharded_index(tmp_path):
    """Multi-shard checkpoints resolve through the index.json weight map."""
    model, _ = _save_ckpt(tmp_path, max_shard_size=256 << 10)  # force shards
    import os

    assert any(f.endswith(".index.json") for f in os.listdir(tmp_path))
    entries = checkpoint_entries(str(tmp_path))
    n = sum(int(np.prod(shape)) for shape, _ in entries.values())
    assert n == param_count(model.config)


def test_estimate_cli_prints_checkpoint_table(tmp_path, capsys):
    _save_ckpt(tmp_path)
    args = argparse.Namespace(model_name=str(tmp_path), dtypes=["bfloat16"])
    assert run(args) == 0
    out = capsys.readouterr().out
    assert "Checkpoint:" in out and "bfloat16" in out and "Largest tensor:" in out


def test_estimate_cli_registry_name_still_works(capsys):
    args = argparse.Namespace(model_name="llama-tiny", dtypes=["float32"])
    assert run(args) == 0
    assert "parameters" in capsys.readouterr().out


def test_find_tied_parameters_shared_array():
    shared = np.ones((4, 4), np.float32)
    tree = {"embed": {"w": shared}, "head": {"w": shared}, "other": np.zeros((2,))}
    assert find_tied_parameters(tree) == [["embed/w", "head/w"]]


def test_find_tied_parameters_numpy_views():
    base = np.arange(16, dtype=np.float32)
    tree = {"a": base.reshape(4, 4), "b": base.reshape(2, 8)}
    assert find_tied_parameters(tree) == [["a", "b"]]


def test_find_tied_parameters_none_for_distinct():
    tree = {"a": np.ones((2,)), "b": np.ones((2,))}
    assert find_tied_parameters(tree) == []


def test_retie_parameters_restores_sharing():
    """A load that materialized duplicates gets its ties re-established."""
    shared = jnp.ones((3, 3))
    tree = {"embed": {"w": shared}, "head": {"w": shared}}
    groups = find_tied_parameters(tree)
    # simulate a loader writing fresh copies
    loaded = {
        "embed": {"w": jnp.asarray(np.full((3, 3), 2.0))},
        "head": {"w": jnp.asarray(np.full((3, 3), 2.0))},
    }
    assert find_tied_parameters(loaded) == []
    retie_parameters(loaded, groups)
    assert loaded["embed"]["w"] is loaded["head"]["w"]
    assert find_tied_parameters(loaded) == [["embed/w", "head/w"]]


def test_find_tied_parameters_disjoint_slices_not_tied():
    """Disjoint slices of one flat buffer are distinct tensors (review repro)."""
    base = np.arange(16, dtype=np.float32)
    tree = {"a": base[:8], "b": base[8:]}
    assert find_tied_parameters(tree) == []
