"""HF-layout checkpoint interop for gpt2/bert/t5 — numerical parity against
real ``transformers`` models (the reference loads any Hub checkpoint;
utils/modeling.py:1541). Llama's importer is covered in test_hf_import.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import GPT2, T5, Bert, get_config
from accelerate_tpu.utils.hf_import import (
    export_hf_family,
    import_hf_family,
    load_checkpoint_in_model,
    looks_like_hf_checkpoint,
)

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _hf_gpt2():
    cfg = transformers.GPT2Config(
        vocab_size=1024, n_positions=256, n_embd=128, n_layer=2, n_head=4,
        activation_function="gelu_new",
    )
    torch.manual_seed(0)
    return transformers.GPT2LMHeadModel(cfg).eval()


def _hf_bert():
    cfg = transformers.BertConfig(
        vocab_size=1024, hidden_size=128, num_hidden_layers=2, num_attention_heads=2,
        intermediate_size=512, max_position_embeddings=128, num_labels=2,
    )
    torch.manual_seed(0)
    return transformers.BertForSequenceClassification(cfg).eval()


def _hf_t5():
    cfg = transformers.T5Config(
        vocab_size=1024, d_model=128, d_kv=32, d_ff=256, num_layers=2, num_heads=4,
        relative_attention_num_buckets=8, relative_attention_max_distance=32,
        feed_forward_proj="relu", tie_word_embeddings=True, dropout_rate=0.0,
    )
    torch.manual_seed(0)
    return transformers.T5ForConditionalGeneration(cfg).eval()


def _state_dict(hf_model):
    return {k: v.numpy() for k, v in hf_model.state_dict().items()}


def test_gpt2_import_matches_transformers_forward():
    hf = _hf_gpt2()
    cfg = get_config("gpt2-tiny")
    params = import_hf_family(_state_dict(hf), cfg)
    ids = np.random.default_rng(0).integers(0, 1024, (2, 16))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(GPT2(cfg).apply(params, jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(want, got, atol=1e-4)


def test_bert_import_matches_transformers_forward():
    hf = _hf_bert()
    cfg = get_config("bert-tiny")
    params = import_hf_family(_state_dict(hf), cfg)
    ids = np.random.default_rng(1).integers(0, 1024, (2, 16))
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(Bert(cfg).apply(params, jnp.asarray(ids, jnp.int32)))
    np.testing.assert_allclose(want, got, atol=1e-4)


def test_t5_import_matches_transformers_forward():
    hf = _hf_t5()
    cfg = get_config("t5-tiny")
    params = import_hf_family(_state_dict(hf), cfg)
    rng = np.random.default_rng(2)
    enc = rng.integers(0, 1024, (2, 12))
    dec = rng.integers(0, 1024, (2, 8))
    with torch.no_grad():
        want = hf(input_ids=torch.tensor(enc), decoder_input_ids=torch.tensor(dec)).logits.numpy()
    got = np.asarray(
        T5(cfg).apply(params, jnp.asarray(enc, jnp.int32), jnp.asarray(dec, jnp.int32))
    )
    np.testing.assert_allclose(want, got, atol=1e-3)


@pytest.mark.parametrize("arch,model_cls", [("gpt2", GPT2), ("bert", Bert), ("t5", T5)])
def test_export_import_roundtrip(arch, model_cls):
    cfg = get_config(f"{arch}-tiny")
    model = model_cls(cfg)
    params = jax.device_get(model.init(jax.random.key(0)))
    flat = export_hf_family(params, cfg)
    assert looks_like_hf_checkpoint(flat)
    back = import_hf_family(flat, cfg)
    from accelerate_tpu.utils.modeling import _iter_flat

    original = dict(_iter_flat(params))
    restored = dict(_iter_flat(back))
    assert original.keys() == restored.keys()
    for key in original:
        np.testing.assert_array_equal(
            np.asarray(original[key]), np.asarray(restored[key]), err_msg=key
        )


def test_wrong_config_fails_loudly():
    hf = _hf_gpt2()
    bad = get_config("gpt2-tiny").replace(intermediate_size=384)
    with pytest.raises((KeyError, ValueError)):
        import_hf_family(_state_dict(hf), bad)


def test_load_checkpoint_in_model_routes_by_arch(tmp_path):
    """An HF-layout t5 checkpoint on disk loads through the generic entry."""
    from accelerate_tpu.checkpointing import _save_flat

    hf = _hf_t5()
    _save_flat(_state_dict(hf), str(tmp_path / "model.safetensors"), True)
    cfg = get_config("t5-tiny")
    model = T5(cfg)
    params = load_checkpoint_in_model(model, str(tmp_path))
    enc = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    dec = jnp.asarray([[0, 5]], jnp.int32)
    out = model.apply(params, enc, dec)
    assert np.isfinite(np.asarray(out)).all()


def test_t5_untied_lm_head_raises():
    """tie_word_embeddings=False checkpoints must fail loudly, not produce
    silently wrong logits from the tied path (review repro)."""
    hf = _hf_t5()
    sd = _state_dict(hf)
    sd["lm_head.weight"] = np.random.default_rng(0).normal(size=sd["shared.weight"].shape).astype(np.float32)
    with pytest.raises(ValueError, match="UNTIED"):
        import_hf_family(sd, get_config("t5-tiny"))
