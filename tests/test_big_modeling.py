"""Big-model inference tests (reference tests/test_big_modeling.py, 1017 LoC):
abstract init, auto device maps, dispatch/offload equivalence, generation,
and the generic stream protocol (arbitrary-model dispatch, hooks.py:212)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.big_modeling import (
    cpu_offload,
    disk_offload,
    dispatch_model,
    init_empty_weights,
    load_checkpoint_and_dispatch,
)
from accelerate_tpu.checkpointing import save_model_weights
from accelerate_tpu.models import Llama
from accelerate_tpu.models.generation import generate
from accelerate_tpu.utils.modeling import (
    check_device_map,
    compute_module_sizes,
    get_max_memory,
    infer_auto_device_map,
    named_component_sizes,
)


@pytest.fixture(scope="module")
def tiny():
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (2, 12)), jnp.int32)
    full_logits = model.apply(params, ids)
    return model, params, ids, full_logits


def test_init_empty_weights_allocates_nothing(tiny):
    model, params, *_ = tiny
    abstract = init_empty_weights(model)
    assert isinstance(abstract["embed_tokens"], jax.ShapeDtypeStruct)
    assert abstract["layers"]["wq"].shape == params["layers"]["wq"].shape


def test_named_component_sizes(tiny):
    model, params, *_ = tiny
    sizes = named_component_sizes(model, dtype_bytes=4)
    # layers.<i> all equal, embed correct
    assert sizes["embed_tokens"] == 1024 * 128 * 4
    assert sizes["layers.0"] == sizes["layers.1"]
    total_expected = sum(int(np.prod(p.shape)) * 4 for p in jax.tree.leaves(params))
    assert compute_module_sizes(model, 4)[""] == total_expected


def test_infer_auto_device_map_spills_in_order(tiny):
    model, *_ = tiny
    sizes = named_component_sizes(model, dtype_bytes=2)
    largest = max(v for k, v in sizes.items() if k.startswith("layers."))
    resident = sum(v for k, v in sizes.items() if not k.startswith("layers."))
    # budget: resident components + layer0 + double-buffer headroom only
    budget = resident + sizes["layers.0"] + 2 * largest + 1
    device_map = infer_auto_device_map(model, max_memory={"device": budget, "cpu": 10**9})
    assert device_map["embed_tokens"] == "device"
    assert device_map["layers.0"] == "device"
    assert device_map["layers.1"] == "cpu"
    check_device_map(model, device_map)


def test_check_device_map_missing(tiny):
    model, *_ = tiny
    with pytest.raises(ValueError, match="does not cover"):
        check_device_map(model, {"embed_tokens": "device"})


def test_get_max_memory_probes():
    budget = get_max_memory()
    assert budget["cpu"] > 0
    assert "device" in budget


def test_dispatch_all_device_matches_full(tiny):
    model, params, ids, full_logits = tiny
    cfg = model.config
    dm = {"embed_tokens": "device", "final_norm": "device", "lm_head": "device"}
    dm.update({f"layers.{i}": "device" for i in range(cfg.num_layers)})
    streamed = dispatch_model(model, params, dm, dtype=jnp.float32)
    got = streamed(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full_logits), atol=1e-4)


def test_cpu_offload_matches_full(tiny):
    model, params, ids, full_logits = tiny
    streamed = cpu_offload(model, params, dtype=jnp.float32)
    got = streamed(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full_logits), atol=1e-4)


def test_disk_offload_matches_full(tiny, tmp_path):
    model, params, ids, full_logits = tiny
    streamed = disk_offload(model, params, str(tmp_path / "offload"), dtype=jnp.float32)
    got = streamed(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full_logits), atol=1e-4)
    # memmap files exist
    assert (tmp_path / "offload" / "index.json").exists()
    assert any(f.suffix == ".dat" for f in (tmp_path / "offload").iterdir())


def test_load_checkpoint_and_dispatch(tiny, tmp_path):
    model, params, ids, full_logits = tiny
    save_model_weights(params, str(tmp_path / "ckpt"))
    streamed = load_checkpoint_and_dispatch(
        model, str(tmp_path / "ckpt"), device_map="auto", dtype=jnp.float32
    )
    got = streamed(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full_logits), atol=1e-4)


def test_generate_kv_cache_matches_recompute(tiny):
    """Cached decode must produce the same tokens as full-recompute argmax."""
    model, params, ids, _ = tiny
    out = generate(model, params, ids, max_new_tokens=5)
    assert out.shape == (2, 17)

    # manual recompute: greedy next-token using full forward each step
    manual = np.asarray(ids)
    for _ in range(5):
        logits = model.apply(params, jnp.asarray(manual))
        nxt = np.argmax(np.asarray(logits[:, -1], np.float32), axis=-1)
        manual = np.concatenate([manual, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, manual)


def test_streamed_generate_matches_generate(tiny):
    model, params, ids, _ = tiny
    expected = generate(model, params, ids, max_new_tokens=4)
    streamed = cpu_offload(model, params, dtype=jnp.float32)
    got = streamed.generate(ids, max_new_tokens=4)
    np.testing.assert_array_equal(got, expected)
    # return_device defers the single host fetch to the caller
    dev = streamed.generate(ids, max_new_tokens=4, return_device=True)
    np.testing.assert_array_equal(np.asarray(dev), expected)


def test_generate_return_device_parity_and_eos(tiny):
    """return_device must yield the same ids as the host path (as a device
    array) — including with eos_token_id, whose done-mask now runs on device
    so the two options compose instead of raising."""
    model, params, ids, _ = tiny
    host = generate(model, params, ids, max_new_tokens=4)
    dev = generate(model, params, ids, max_new_tokens=4, return_device=True)
    assert not isinstance(dev, np.ndarray)
    np.testing.assert_array_equal(np.asarray(dev), host)
    host_eos = generate(model, params, ids, max_new_tokens=4, eos_token_id=0)
    dev_eos = generate(model, params, ids, max_new_tokens=4, eos_token_id=0, return_device=True)
    assert not isinstance(dev_eos, np.ndarray)
    np.testing.assert_array_equal(np.asarray(dev_eos), host_eos)


def test_streaming_group_size_invariance(tiny):
    """Grouped layer execution (1 dispatch per group) must not change results;
    a tiny window forces group_size=1, the default fuses all layers."""
    from accelerate_tpu.big_modeling import dispatch_model

    model, params, ids, full_logits = tiny
    cfg = model.config
    dm = {"embed_tokens": "device", "final_norm": "device", "lm_head": "device"}
    dm.update({f"layers.{i}": "cpu" for i in range(cfg.num_layers)})

    wide = dispatch_model(model, params, dm, dtype=jnp.float32)
    narrow = dispatch_model(model, params, dm, dtype=jnp.float32, stream_window_bytes=1)
    assert narrow.group_size == 1 and wide.group_size > 1
    np.testing.assert_allclose(np.asarray(wide(ids)), np.asarray(full_logits), atol=1e-4)
    np.testing.assert_allclose(np.asarray(narrow(ids)), np.asarray(full_logits), atol=1e-4)
    np.testing.assert_array_equal(
        wide.generate(ids, max_new_tokens=3), narrow.generate(ids, max_new_tokens=3)
    )


def test_streamed_forward_device_footprint_bounded(tiny, monkeypatch):
    """The memory invariant of the reference's big-model table
    (benchmarks/README.md:44-46, peak == resident + buffers): the streaming
    executor holds at most the resident components plus a double-buffered
    group window on device. Measured with jax.live_arrays() at every group
    boundary — tunneled TPU transports expose no memory_stats, so this test
    is the enforcement of what bench.py's bigmodel sections report."""
    from accelerate_tpu import big_modeling
    from accelerate_tpu.models.config import get_config

    # 4 layers: with a 2-group double buffer the stack must NOT fit on device
    cfg = get_config("llama-tiny").replace(num_layers=4)
    model = Llama(cfg)
    params = model.init(jax.random.key(0))
    ids = tiny[2]
    full_logits = model.apply(params, ids)
    dm = {"embed_tokens": "device", "final_norm": "device", "lm_head": "device"}
    dm.update({f"layers.{i}": "cpu" for i in range(cfg.num_layers)})
    lm = big_modeling.dispatch_model(model, params, dm, dtype=jnp.float32, stream_window_bytes=1)
    assert lm.group_size == 1 and cfg.num_layers >= 4  # multiple staged groups

    def live_bytes() -> int:
        return sum(a.nbytes for a in jax.live_arrays())

    baseline = live_bytes()  # params fixture + lm's resident components
    samples: list[int] = []
    orig = big_modeling.StreamedModel._iter_device_layer_groups

    def instrumented(self):
        # samples land when the PREVIOUS group is still consumer-referenced
        # and the next is staged — the double-buffer peak
        for staged in orig(self):
            samples.append(live_bytes())
            yield staged

    monkeypatch.setattr(big_modeling.StreamedModel, "_iter_device_layer_groups", instrumented)
    out = lm(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full_logits), atol=1e-4)
    assert len(samples) == cfg.num_layers  # group_size=1: one sample per layer
    window = 2 * lm.group_size * lm._layer_bytes()
    activations = 4 << 20  # carry + logits temporaries for the tiny model
    assert max(samples) - baseline <= window + activations
    # and the full offloaded stack genuinely does NOT fit the window
    assert window < len(lm.layer_buffers) * lm._layer_bytes()


# -- generic (non-llama) dispatch via the stream protocol --------------------


@pytest.fixture(scope="module")
def tiny_bert():
    from accelerate_tpu.models import Bert

    model = Bert("bert-tiny")
    params = model.init(jax.random.key(1))
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, 1024, (2, 10)), jnp.int32)
    mask = jnp.asarray([[1] * 10, [1] * 7 + [0] * 3], jnp.int32)
    types = jnp.asarray(rng.integers(0, 2, (2, 10)), jnp.int32)
    full = model.apply(params, ids, mask, types)
    return model, params, (ids, mask, types), full


def test_dispatch_bert_all_device(tiny_bert):
    """A model the module never special-cased dispatches via the protocol."""
    model, params, inputs, full = tiny_bert
    sizes = named_component_sizes(model)
    device_map = {k: "device" for k in sizes}
    streamed = dispatch_model(model, params, device_map, dtype=jnp.float32)
    got = streamed(*inputs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full), atol=1e-5)


def test_cpu_offload_bert_matches_full(tiny_bert):
    model, params, inputs, full = tiny_bert
    streamed = cpu_offload(model, params, dtype=jnp.float32)
    got = streamed(*inputs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full), atol=1e-5)
    # offloaded: every layer buffer lives on host
    assert not any(streamed.layer_on_device)


def test_disk_offload_bert_matches_full(tiny_bert, tmp_path):
    model, params, inputs, full = tiny_bert
    streamed = disk_offload(model, params, str(tmp_path), dtype=jnp.float32)
    got = streamed(*inputs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full), atol=1e-5)


def test_dispatch_unsupported_model_raises():
    class NotStreamable:
        pass

    with pytest.raises(TypeError, match="stream"):
        dispatch_model(NotStreamable(), {"layers": {"w": np.zeros((2, 4))}}, {"layers.0": "device", "layers.1": "device"})


def test_auto_device_map_for_generic_model(tiny_bert):
    """device_map='auto' must work for the generic protocol too."""
    model, params, inputs, full = tiny_bert
    streamed = dispatch_model(model, params, device_map="auto", dtype=jnp.float32)
    got = streamed(*inputs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full), atol=1e-5)


# -- evict/restore + cpu_offload_with_hook (reference big_modeling.py:215-302) --


def test_evict_restore_roundtrip():
    """evict() moves every device-placed buffer to its host shadow; restore()
    (and implicit restore on execution) brings back identical outputs."""
    from accelerate_tpu.big_modeling import make_layered_device_map

    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    lm = dispatch_model(
        model, params, make_layered_device_map(model, "device"), dtype=jnp.float32
    )
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (1, 8)), jnp.int32)
    before = np.asarray(lm(ids))
    assert all(lm.layer_on_device)

    lm.evict()
    assert not any(lm.layer_on_device)
    assert all(isinstance(v, np.ndarray) for v in lm.resident.values())

    after_evicted = np.asarray(lm(ids))  # implicit restore
    assert all(lm.layer_on_device)
    np.testing.assert_allclose(before, after_evicted, atol=1e-5)


def test_cpu_offload_with_hook_pipeline_of_models():
    """Two dispatched models run alternately within one HBM budget: executing
    model B evicts model A first (prev_module_hook chaining)."""
    from accelerate_tpu import cpu_offload_with_hook

    model_a = Llama("llama-tiny")
    params_a = model_a.init(jax.random.key(1))
    model_b = Llama("llama-tiny")
    params_b = model_b.init(jax.random.key(2))

    lm_a, hook_a = cpu_offload_with_hook(model_a, params_a, dtype=jnp.float32)
    lm_b, hook_b = cpu_offload_with_hook(model_b, params_b, dtype=jnp.float32, prev_module_hook=hook_a)

    ids = jnp.asarray(np.random.default_rng(3).integers(0, 1024, (1, 8)), jnp.int32)
    out_a = np.asarray(lm_a(ids))
    assert all(lm_a.layer_on_device)
    out_b = np.asarray(lm_b(ids))
    # running B evicted A
    assert not any(lm_a.layer_on_device) and all(lm_b.layer_on_device)
    # looping B does not touch A again; A restores transparently when reused
    np.testing.assert_allclose(np.asarray(lm_b(ids)), out_b, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lm_a(ids)), out_a, atol=1e-5)
    hook_b.offload()
    assert not any(lm_b.layer_on_device)


def test_evicted_generate_restores():
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(4))
    from accelerate_tpu.big_modeling import make_layered_device_map

    lm = dispatch_model(
        model, params, make_layered_device_map(model, "device"), dtype=jnp.float32
    )
    ids = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    want = lm.generate(ids, max_new_tokens=4)
    lm.evict()
    got = lm.generate(ids, max_new_tokens=4)
    np.testing.assert_array_equal(want, got)


def test_auto_device_map_for_configless_model():
    """Component sizing works for arbitrary models without a registry config:
    the layer count comes from the stacked tree itself (reference
    modeling.py:606-693 operates on any nn.Module)."""
    from accelerate_tpu.utils.modeling import named_component_sizes

    class Custom:
        def init(self, rng):
            del rng
            return {
                "embed": jnp.zeros((16, 8)),
                "layers": {"w": jnp.zeros((3, 8, 8)), "b": jnp.zeros((3, 8))},
            }

        def stream_prefix(self, resident, x):
            return x

        def stream_layer(self, carry, lp):
            return carry @ lp["w"] + lp["b"]

        def stream_suffix(self, resident, carry):
            return carry

    sizes = named_component_sizes(Custom(), dtype_bytes=4)
    assert sizes["embed"] == 16 * 8 * 4
    assert sizes["layers.0"] == sizes["layers.2"] == (8 * 8 + 8) * 4
    assert "layers.3" not in sizes

    # and the full dispatch pipeline runs on it
    model = Custom()
    params = jax.device_get(model.init(None))
    streamed = dispatch_model(model, params, device_map="auto", dtype=jnp.float32)
    out = streamed(jnp.ones((2, 8)))
    assert out.shape == (2, 8)


def test_cpu_offload_with_hook_starts_evicted():
    """Construction is HBM-free (reference semantics: resident only from the
    first forward) — chaining N models never uploads more than one."""
    from accelerate_tpu import cpu_offload_with_hook

    model = Llama("llama-tiny")
    params = model.init(jax.random.key(7))
    lm, hook = cpu_offload_with_hook(model, params, dtype=jnp.float32)
    assert not any(lm.layer_on_device)  # nothing resident yet
    ids = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = np.asarray(lm(ids))
    assert all(lm.layer_on_device)  # first execution uploaded everything
    assert np.isfinite(out).all()
    hook.offload()
    assert not any(lm.layer_on_device)


def test_streamed_bert_ignores_stale_ring_hook():
    """A mesh-bound attention hook left on the model must not hijack the
    single-device streaming path (it would drop the padding mask)."""
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.models import Bert

    model = Bert("bert-tiny")
    params = jax.device_get(model.init(jax.random.key(8)))
    rng = np.random.default_rng(8)
    ids = jnp.asarray(rng.integers(0, 1024, (2, 16)), jnp.int32)
    am = jnp.asarray([[1] * 16, [1] * 9 + [0] * 7], jnp.int32)
    want = np.asarray(model.apply(params, ids, attention_mask=am))

    Accelerator(parallelism=ParallelismConfig(sequence=4)).prepare_model(model, params=params)
    assert model.attention_fn is not None  # ring hook installed
    streamed = cpu_offload(model, params, dtype=jnp.float32)
    got = np.asarray(streamed(ids, am))
    np.testing.assert_allclose(want, got, atol=1e-4)
