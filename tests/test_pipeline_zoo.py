"""Pipeline generality across the model zoo (gpt2/bert), dropout-through-
pipeline, MoE aux loss through pipeline, and per-row positions.

VERDICT r3 items #2 and #5: the schedule must be model-agnostic (reference
generality analogue: hooks.py:120-176 attach to arbitrary modules) and must
support standard training regularization (dropout, MoE balance loss).
"""

import dataclasses

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import Bert, GPT2, Llama, get_config


def test_gpt2_pipeline_forward_matches_single_device():
    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (8, 16)), jnp.int32)
    expected = model.apply(params, ids)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    assert model.pipeline_fn is not None
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_gpt2_pipeline_with_mask_matches():
    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(1))
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 1024, (8, 16)), jnp.int32)
    am = np.ones((8, 16), np.int32)
    am[0, :5] = 0
    am[3, :2] = 0
    am = jnp.asarray(am)
    expected = model.apply(params, ids, attention_mask=am)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(ids, attention_mask=am)
    real = np.asarray(am, bool)
    np.testing.assert_allclose(np.asarray(expected)[real], np.asarray(got)[real], atol=2e-4)


def test_gpt2_pipeline_trains():
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, data=4))
    model = GPT2("gpt2-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    loss_fn = GPT2.loss_fn(model)
    batch = {"input_ids": jnp.asarray(np.random.default_rng(0).integers(0, 1024, (8, 32)), jnp.int32)}
    losses = []
    for _ in range(6):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_bert_pipeline_forward_matches_single_device():
    model = Bert("bert-tiny")
    params = model.init(jax.random.key(2))
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, 1024, (8, 16)), jnp.int32)
    am = np.ones((8, 16), np.int32)
    am[1, 10:] = 0
    am = jnp.asarray(am)
    expected = model.apply(params, ids, attention_mask=am)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    assert model.pipeline_fn is not None
    got = prepared(ids, attention_mask=am)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_bert_pipeline_params_sharded_over_pipeline_axis():
    model = Bert("bert-tiny")
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model)
    assert prepared.params["layers"]["wq"].sharding.spec[0] == "pipeline"
    assert prepared.params["layers"]["attn_norm_scale"].sharding.spec[0] == "pipeline"


# -- dropout through the pipeline (VERDICT r3 #5) ---------------------------


def _dropout_llama(seed=0):
    cfg = dataclasses.replace(get_config("llama-tiny"), dropout_rate=0.3)
    model = Llama(cfg)
    params = model.init(jax.random.key(seed))
    return model, params


def test_llama_pipeline_dropout_matches_fold_reference():
    """Pipeline forward with dropout == a non-pipeline forward applying the
    SAME per-(layer, microbatch) rng fold (pipeline.fold_pipeline_dropout_rng)
    to each microbatch independently."""
    from accelerate_tpu.models.attention import rotary_embedding
    from accelerate_tpu.models.llama import decoder_layer, rms_norm
    from accelerate_tpu.parallel.pipeline import fold_pipeline_dropout_rng

    model, params = _dropout_llama(seed=7)
    cfg = model.config
    b, s = 8, 16
    ids = jnp.asarray(np.random.default_rng(7).integers(0, 1024, (b, s)), jnp.int32)
    key = jax.random.key(42)

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    num_micro = 4 * 2  # prepare_model default: 4 per stage
    M_eff = min(num_micro, b)
    got = model.apply(prepared.params, ids, dropout_rng=key)

    # reference: per-microbatch layer loop with the same fold rule
    cos, sin = rotary_embedding(jnp.arange(s)[None, :], cfg.dim_per_head, cfg.rope_theta)
    outs = []
    for m in range(M_eff):
        h = jnp.take(params["embed_tokens"], ids[m * (b // M_eff):(m + 1) * (b // M_eff)], axis=0)
        for l in range(cfg.num_layers):
            lp = jax.tree.map(lambda x: x[l], params["layers"])
            rngs = tuple(jax.random.split(fold_pipeline_dropout_rng(key, l, m)))
            h, _ = decoder_layer(
                cfg, h, lp, cos, sin, None, causal=True,
                dropout_rngs=rngs, dropout_rate=cfg.dropout_rate,
            )
        outs.append(h)
    h = jnp.concatenate(outs, axis=0)
    h = rms_norm(h, params["final_norm"], cfg.norm_eps)
    head = params["embed_tokens"].T if cfg.tie_embeddings else params["lm_head"]
    expected = h @ head.astype(h.dtype)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_llama_pipeline_dropout_trains():
    """A llama with standard training regularization trains under pipeline=2."""
    cfg = dataclasses.replace(get_config("llama-tiny"), dropout_rate=0.1)
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, data=4))
    model = Llama(cfg)
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))

    def loss_fn(params, batch):
        logits = model.apply(
            params, batch["input_ids"], dropout_rng=batch["dropout_rng"]
        ).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        tgt = batch["input_ids"][:, 1:]
        return -jnp.take_along_axis(logp, tgt[..., None], axis=-1).mean()

    ids = jnp.asarray(np.random.default_rng(8).integers(0, 1024, (8, 32)), jnp.int32)
    losses = []
    for i in range(8):
        batch = {"input_ids": ids, "dropout_rng": jax.random.key(i)}
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_gpt2_pipeline_dropout_runs():
    """Dropout threads through the schedule for every hooked family."""
    cfg = dataclasses.replace(get_config("gpt2-tiny"), dropout_rate=0.2)
    model = GPT2(cfg)
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model)
    ids = jnp.asarray(np.random.default_rng(9).integers(0, 1024, (8, 16)), jnp.int32)
    out = model.apply(prepared.params, ids, dropout_rng=jax.random.key(0))
    assert np.isfinite(np.asarray(out)).all()
    # dropout must actually fire (different rng -> different logits)
    out2 = model.apply(prepared.params, ids, dropout_rng=jax.random.key(1))
    assert not np.allclose(np.asarray(out), np.asarray(out2))


# -- MoE balance loss through the pipeline (VERDICT r3 #5) ------------------


def test_moe_aux_threads_through_pipeline_single_microbatch():
    """With one microbatch the pipeline's per-microbatch aux equals the
    non-pipeline full-batch aux exactly."""
    from accelerate_tpu.utils import ModelParallelPlugin

    model = Llama("llama-moe-tiny")
    params = model.init(jax.random.key(3))
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 1024, (4, 16)), jnp.int32)
    logits_ref, aux_ref = model.apply(params, ids, return_aux=True)
    model.pipeline_fn = None

    accelerator = Accelerator(
        parallelism=ParallelismConfig(pipeline=2),
        model_parallel_plugin=ModelParallelPlugin(pipeline_size=2, num_microbatches=1),
    )
    accelerator.prepare_model(model, params=params)
    assert model.pipeline_fn is not None
    logits, aux = model.apply(params, ids, return_aux=True)
    np.testing.assert_allclose(np.asarray(logits_ref), np.asarray(logits), atol=2e-4)
    np.testing.assert_allclose(float(aux_ref), float(aux), atol=1e-5)
    assert float(aux) > 0.0  # the balance term is real, not a passthrough zero


def test_moe_pipeline_trains_with_balance_loss():
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, expert=4))
    model = Llama("llama-moe-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    loss_fn = Llama.loss_fn(model)  # includes the aux term for MoE configs
    batch = {"input_ids": jnp.asarray(np.random.default_rng(4).integers(0, 1024, (8, 32)), jnp.int32)}
    losses = []
    for _ in range(6):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


# -- per-row positions (previously rejected, pipeline.py r3:240) ------------


def test_pipeline_per_row_positions_matches():
    """cos/sin with a real batch dim ride the schedule as per-microbatch side
    inputs instead of being rejected."""
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(5))
    rng = np.random.default_rng(5)
    ids = jnp.asarray(rng.integers(0, 1024, (8, 16)), jnp.int32)
    positions = jnp.asarray(rng.integers(0, 64, (8, 1)), jnp.int32) + jnp.arange(16)[None, :]
    expected = model.apply(params, ids, positions=positions)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    got = model.apply(prepared.params, ids, positions=positions)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_llama_pipeline_with_flash_attention_matches():
    """The attention_fn hook (flash kernel on TPU) applies inside the
    pipeline schedule. On the CPU mesh the wrapper's manual-region interpret
    fallback keeps the math exact (einsum), so this validates the hook
    wiring + kv_mask threading; the kernel itself lowers via Mosaic on TPU."""
    from accelerate_tpu.ops.flash_attention import make_auto_attention

    model = Llama("llama-tiny")
    params = model.init(jax.random.key(10))
    ids = jnp.asarray(np.random.default_rng(10).integers(0, 1024, (8, 128)), jnp.int32)
    expected = model.apply(params, ids)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    model.attention_fn = make_auto_attention(min_seq=128)  # force (CPU = interpret mode)
    got = model.apply(prepared.params, ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-3)


# -- pipeline x sequence (previously raised NotImplementedError) ------------


def test_llama_pipeline_sequence_forward_matches():
    """The schedule goes manual over BOTH axes; each stage runs ring
    attention over its sequence shard."""
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(20))
    ids = jnp.asarray(np.random.default_rng(20).integers(0, 1024, (8, 32)), jnp.int32)
    expected = model.apply(params, ids)
    model.pipeline_fn = model.attention_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, sequence=2, data=2))
    prepared = accelerator.prepare_model(model, params=params)
    assert model.pipeline_fn is not None and model.attention_fn is not None
    got = model.apply(prepared.params, ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_llama_pipeline_sequence_padded_matches():
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(21))
    ids = jnp.asarray(np.random.default_rng(21).integers(0, 1024, (4, 32)), jnp.int32)
    am = np.ones((4, 32), np.int32)
    am[0, :10] = 0
    am = jnp.asarray(am)
    expected = model.apply(params, ids, attention_mask=am)
    model.pipeline_fn = model.attention_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, sequence=2))
    prepared = accelerator.prepare_model(model, params=params)
    got = model.apply(prepared.params, ids, attention_mask=am)
    real = np.asarray(am, bool)
    np.testing.assert_allclose(np.asarray(expected)[real], np.asarray(got)[real], atol=2e-4)


def test_llama_pipeline_sequence_trains():
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, sequence=2, data=2))
    model = Llama("llama-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    loss_fn = Llama.loss_fn(model)
    batch = {"input_ids": jnp.asarray(np.random.default_rng(22).integers(0, 1024, (8, 64)), jnp.int32)}
    losses = []
    for _ in range(6):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_gpt2_pipeline_sequence_forward_matches():
    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(23))
    ids = jnp.asarray(np.random.default_rng(23).integers(0, 1024, (8, 32)), jnp.int32)
    expected = model.apply(params, ids)
    model.pipeline_fn = model.attention_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, sequence=2))
    prepared = accelerator.prepare_model(model, params=params)
    got = model.apply(prepared.params, ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_t5_pipeline_sequence_still_raises():
    """T5 declares no sequence dims (its rel-bias attention has no ring) —
    asking for both axes must stay loud."""
    from accelerate_tpu.models import T5

    model = T5("t5-tiny")
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, sequence=2))
    with pytest.raises(NotImplementedError, match="sequence"):
        accelerator.prepare_model(model)


def test_llama_pipeline_sequence_bf16_full_step():
    """Regression: bf16 + pp x sp crashed XLA's AllReducePromotion via the
    layers' sequence-replication pcast transposing to a bf16 psum."""
    import optax as _optax

    accelerator = Accelerator(
        mixed_precision="bf16",
        parallelism=ParallelismConfig(pipeline=2, sequence=2, data=2),
    )
    model = Llama("llama-tiny")
    accelerator.prepare_model(model)
    accelerator.prepare_optimizer(_optax.adamw(1e-3))
    step = accelerator.compiled_step(Llama.loss_fn(model), clip_grad_norm=1.0)
    ids = jnp.asarray(np.random.default_rng(24).integers(0, 1024, (8, 64)), jnp.int32)
    batch = {"input_ids": jax.device_put(ids, accelerator.state.data_sharding())}
    losses = [float(step(batch)) for _ in range(3)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_pipeline_sequence_dropout_differs_across_shards():
    """Each sequence shard must draw its own dropout mask (review repro:
    without the axis fold, global positions j and j+S/2 got identical masks)."""
    cfg = dataclasses.replace(get_config("llama-tiny"), dropout_rate=0.5)
    model = Llama(cfg)
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, sequence=2))
    prepared = accelerator.prepare_model(model)
    # identical token at every position: any output difference within a row
    # can come only from position embeddings (none between equal rotary
    # phases? rotary differs by position) — instead compare the DROPPED
    # pattern: run twice with the same rng; determinism must hold...
    ids = jnp.full((4, 32), 7, jnp.int32)
    out1 = model.apply(prepared.params, ids, dropout_rng=jax.random.key(3))
    out2 = model.apply(prepared.params, ids, dropout_rng=jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))  # deterministic
    out3 = model.apply(prepared.params, ids, dropout_rng=jax.random.key(4))
    assert not np.allclose(np.asarray(out1), np.asarray(out3))  # rng-sensitive
