"""pod-launch supervision: exit propagation, dead-host kill, heartbeat,
restart (VERDICT r4 missing #2 — torchrun-elastic analogue)."""

import subprocess
import sys
import time

import pytest

from accelerate_tpu.commands.pod import supervise
from accelerate_tpu.resilience import RetryPolicy

# zero-delay relaunch policy: tests of the restart LOGIC shouldn't wait out
# the production backoff (which has its own test below)
_NO_BACKOFF = RetryPolicy(base_delay=0.0, max_delay=0.0, jitter=0.0)


def _spawn_script(scripts):
    """spawn(i) running scripts[i] with `python -c`."""

    def spawn(i):
        return subprocess.Popen(
            [sys.executable, "-u", "-c", scripts[i]],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    return spawn


def test_all_workers_succeed():
    spawn = _spawn_script(["print('a')", "print('b')"])
    assert supervise(spawn, 2, poll_interval=0.05) == 0


def test_failing_worker_propagates_exit_code_and_kills_peers():
    """One dead host must fail the job loudly, not hang the rendezvous."""
    spawn = _spawn_script([
        "import time; time.sleep(60)",   # healthy worker stuck in 'rendezvous'
        "import sys; sys.exit(3)",       # dead host
    ])
    start = time.monotonic()
    assert supervise(spawn, 2, poll_interval=0.05) == 3
    assert time.monotonic() - start < 30  # did NOT wait out the sleeping peer


def test_heartbeat_kills_silent_worker():
    spawn = _spawn_script([
        "import time\nwhile True:\n    print('step', flush=True)\n    time.sleep(0.05)",
        "import time; time.sleep(60)",   # never prints: silent hang
    ])
    start = time.monotonic()
    assert supervise(spawn, 2, heartbeat_timeout=0.5, poll_interval=0.05) == 124
    assert time.monotonic() - start < 30


def test_restart_on_failure_retries_then_succeeds(tmp_path):
    """First attempt fails, relaunch succeeds (state via a marker file)."""
    marker = tmp_path / "attempted"
    script = (
        f"import os, sys\n"
        f"p = {str(marker)!r}\n"
        f"if os.path.exists(p):\n"
        f"    sys.exit(0)\n"
        f"open(p, 'w').close()\n"
        f"sys.exit(7)\n"
    )
    spawn = _spawn_script([script])
    assert supervise(spawn, 1, restarts=2, poll_interval=0.05, restart_policy=_NO_BACKOFF) == 0
    assert marker.exists()


def test_restarts_exhausted_returns_failure():
    spawn = _spawn_script(["import sys; sys.exit(9)"])
    assert supervise(spawn, 1, restarts=1, poll_interval=0.05, restart_policy=_NO_BACKOFF) == 9


def test_worker_output_is_prefixed(capfd):
    spawn = _spawn_script(["print('hello-from-zero')"])
    assert supervise(spawn, 1, poll_interval=0.05) == 0
    # pump threads race process exit by a hair
    time.sleep(0.2)
    assert "[worker 0] hello-from-zero" in capfd.readouterr().out


def test_cli_debug_prints_per_worker_commands(capsys):
    import argparse

    from accelerate_tpu.commands.pod import run

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, num_workers=2, restart_on_failure=0,
        heartbeat_timeout=0.0, training_script="train.py", training_script_args=[],
    )
    assert run(args) == 0
    out = capsys.readouterr().out
    assert "--worker 0" in out and "--worker 1" in out


def test_supervision_flags_require_num_workers():
    import argparse

    from accelerate_tpu.commands.pod import run

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, num_workers=None, restart_on_failure=2,
        heartbeat_timeout=0.0, training_script="train.py", training_script_args=[],
    )
    with pytest.raises(ValueError, match="num_workers"):
        run(args)


def test_auto_resume_requires_num_workers():
    import argparse

    from accelerate_tpu.commands.pod import run

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, num_workers=None, restart_on_failure=0,
        heartbeat_timeout=0.0, auto_resume=True, training_script="train.py",
        training_script_args=[],
    )
    with pytest.raises(ValueError, match="num_workers"):
        run(args)


def test_auto_resume_requires_restarts():
    """--auto_resume without --restart_on_failure would silently never
    resume (the job dies on first failure) — reject loudly instead."""
    import argparse

    from accelerate_tpu.commands.pod import run

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, num_workers=2, restart_on_failure=0,
        heartbeat_timeout=0.0, auto_resume=True, training_script="train.py",
        training_script_args=[],
    )
    with pytest.raises(ValueError, match="restart_on_failure"):
        run(args)


def test_assemble_worker_command_resume_appends_flag():
    import argparse

    from accelerate_tpu.commands.pod import assemble_worker_command

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, training_script="train.py",
        training_script_args=["--epochs", "3"],
    )
    plain = assemble_worker_command(args)
    resumed = assemble_worker_command(args, resume=True)
    assert plain.endswith("train.py --epochs 3")
    assert resumed.endswith("train.py --epochs 3 --resume auto")


def test_supervise_passes_attempt_to_two_arg_spawn():
    """Relaunch attempts see attempt numbers (the auto-resume hook): the first
    attempt fails, the second — which a real spawn would launch with
    `--resume auto` — succeeds."""
    attempts = []

    def spawn(i, attempt):
        attempts.append((i, attempt))
        code = 5 if attempt == 1 else 0
        return subprocess.Popen(
            [sys.executable, "-c", f"import sys; sys.exit({code})"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    assert supervise(spawn, 1, restarts=1, poll_interval=0.05, restart_policy=_NO_BACKOFF) == 0
    assert attempts == [(0, 1), (0, 2)]


def test_supervise_single_arg_spawn_still_works():
    spawn = _spawn_script(["print('legacy')"])
    assert supervise(spawn, 1, poll_interval=0.05) == 0


# -- resilience-PR satellites: fake-worker heartbeat kill + relaunch backoff --


class _FakeProc:
    """Popen-shaped stub: no subprocess, no gcloud — just a scripted exit."""

    stdout = None

    def __init__(self, returncode=None):
        self._rc = returncode
        self.killed = False

    def poll(self):
        return self._rc

    def kill(self):
        self.killed = True
        self._rc = -9


def test_heartbeat_timeout_kill_path_fake_workers():
    """The heartbeat-timeout kill path with FAKE workers: a worker that never
    produces output must be declared dead (exit 124) and every peer must be
    killed — no real processes involved, so the path is tested in isolation
    from subprocess/pipe timing."""
    procs = [_FakeProc(), _FakeProc()]  # both alive, both silent forever
    start = time.monotonic()
    rc = supervise(
        lambda i: procs[i], 2, heartbeat_timeout=0.2, poll_interval=0.01,
        restart_policy=_NO_BACKOFF,
    )
    assert rc == 124
    assert all(p.killed for p in procs)
    assert time.monotonic() - start < 10


def test_heartbeat_ignores_chatty_workers():
    """Workers whose last_activity keeps advancing are never heartbeat-killed:
    the fleet runs to completion (fakes exit 0 after a few polls)."""
    class Chatty(_FakeProc):
        def __init__(self):
            super().__init__()
            self.polls = 0

        def poll(self):
            self.polls += 1
            return 0 if self.polls > 3 else None

    workers = []

    def spawn(i):
        proc = Chatty()
        workers.append(proc)
        return proc

    rc = supervise(spawn, 2, heartbeat_timeout=5.0, poll_interval=0.01)
    assert rc == 0
    assert not any(w.killed for w in workers)


def test_relaunch_backoff_follows_retry_policy():
    """Satellite: the relaunch delay is the RetryPolicy's jittered-exponential
    backoff, not an immediate restart — attempt N sleeps delay_for(N-1)."""
    sleeps = []
    policy = RetryPolicy(base_delay=0.5, max_delay=4.0, jitter=0.0, sleep=sleeps.append)
    rc = supervise(
        lambda i: _FakeProc(returncode=3), 1, restarts=2, poll_interval=0.01,
        restart_policy=policy,
    )
    assert rc == 3
    assert sleeps == [0.5, 1.0]  # exponential, zero-jitter for determinism


# -- elastic partial-failure mode (ISSUE 13 satellite): one dead worker
# -- signals the survivors to shrink instead of relaunching the fleet


class _ElasticProc(_FakeProc):
    """Fake with a scripted exit schedule + signal recording."""

    def __init__(self, schedule=(None,)):
        super().__init__()
        self.schedule = list(schedule)
        self.signals = []

    def poll(self):
        if self._rc is not None:
            return self._rc
        self._rc = self.schedule.pop(0) if self.schedule else self._rc
        return self._rc

    def send_signal(self, signum):
        self.signals.append(signum)


def test_elastic_partial_failure_signals_survivors_and_continues():
    """One worker dies; elastic mode notifies the survivors (SIGUSR1) and
    keeps supervising them instead of killing the fleet — the job succeeds
    when the shrunken fleet finishes."""
    import signal

    # worker 1 exits 3 immediately; 0 and 2 run a few polls then exit 0
    procs = [
        _ElasticProc([None, None, None, None, 0]),
        _ElasticProc([3]),
        _ElasticProc([None, None, None, None, 0]),
    ]
    rc = supervise(
        lambda i: procs[i], 3, poll_interval=0.01,
        restart_policy=_NO_BACKOFF, partial_failure="elastic",
    )
    assert rc == 0
    assert procs[0].signals == [signal.SIGUSR1]
    assert procs[2].signals == [signal.SIGUSR1]
    assert not procs[0].killed and not procs[2].killed  # survivors never killed


def test_elastic_heartbeat_silent_worker_is_killed_then_fleet_continues():
    """A heartbeat-silent worker is operationally dead: elastic mode kills
    it (instead of the whole fleet) and the remaining worker's clean exit
    ends the job at 0. (The fake survivor finishes inside the heartbeat
    window — fakes have no output pump to keep their heartbeat fresh.)"""
    silent = _ElasticProc([None] * 1000)
    healthy = _ElasticProc([None, None, None, 0])
    procs = [healthy, silent]
    start = time.monotonic()
    rc = supervise(
        lambda i: procs[i], 2, heartbeat_timeout=0.2, poll_interval=0.01,
        restart_policy=_NO_BACKOFF, partial_failure="elastic",
    )
    assert rc == 0
    assert silent.killed
    assert not healthy.killed
    assert time.monotonic() - start < 10


def test_elastic_last_worker_failure_falls_back_to_relaunch_ladder():
    """With no survivors left to shrink onto, elastic mode degrades to the
    normal kill-and-relaunch ladder (here: restarts exhausted → exit code)."""
    rc = supervise(
        lambda i: _ElasticProc([5]), 1, poll_interval=0.01,
        restart_policy=_NO_BACKOFF, partial_failure="elastic",
    )
    assert rc == 5


def test_elastic_double_loss_shrinks_twice():
    """Two separate worker deaths shrink the fleet twice; each surviving
    round is re-signalled and the last worker finishing cleanly ends the
    job at 0."""
    procs = [
        _ElasticProc([None] * 8 + [0]),
        _ElasticProc([2]),
        _ElasticProc([None, None, 4]),
    ]
    rc = supervise(
        lambda i: procs[i], 3, poll_interval=0.01,
        restart_policy=_NO_BACKOFF, partial_failure="elastic",
    )
    assert rc == 0
    assert len(procs[0].signals) == 2  # notified for both losses


def test_elastic_supervisor_publishes_lost_index_to_store(tmp_path):
    """ISSUE 14 satellite: the supervisor KNOWS which worker died — with a
    membership_dir it publishes the index into the rendezvous store before
    signalling, and a survivor-side MembershipService resolves the loss to
    a NAMED host (the pod-launch → store → coordinator path)."""
    import signal

    from accelerate_tpu.resilience import FilesystemStore, MembershipService

    store_dir = str(tmp_path / "membership")
    procs = [
        _ElasticProc([None, None, None, None, 0]),
        _ElasticProc([3]),
    ]
    rc = supervise(
        lambda i: procs[i], 2, poll_interval=0.01,
        restart_policy=_NO_BACKOFF, partial_failure="elastic",
        membership_dir=store_dir,
    )
    assert rc == 0
    assert procs[0].signals == [signal.SIGUSR1]
    store = FilesystemStore(store_dir)
    record = store.read("lost/1")
    assert record is not None
    assert record["source"] == "supervisor"
    assert "exit code 3" in record["reason"]
    # the survivor's detector turns the publication into a named suspicion
    survivor = MembershipService(store, num_hosts=2, host_index=0)
    detections = survivor.detect()
    assert [d["host"] for d in detections] == [1]
    assert detections[0]["reason"] == "supervisor"
    assert detections[0]["mttd_s"] >= 0.0


def test_elastic_multi_sequential_losses_publish_each_and_epochs_increase(tmp_path):
    """Two separate worker deaths publish two lost records; the survivor
    resolving each mints monotonically increasing epochs — the
    multi-sequential-loss drill."""
    from accelerate_tpu.resilience import FilesystemStore, MembershipService

    store_dir = str(tmp_path / "membership")
    procs = [
        _ElasticProc([None] * 8 + [0]),
        _ElasticProc([2]),
        _ElasticProc([None, None, 4]),
    ]
    rc = supervise(
        lambda i: procs[i], 3, poll_interval=0.01,
        restart_policy=_NO_BACKOFF, partial_failure="elastic",
        membership_dir=store_dir,
    )
    assert rc == 0
    store = FilesystemStore(store_dir)
    assert store.read("lost/1") is not None
    assert store.read("lost/2") is not None
    survivor = MembershipService(store, num_hosts=3, host_index=0)
    epochs = [survivor.epoch]
    for detection in survivor.detect():
        epochs.append(survivor.resolve_loss(detection["host"], reason="supervisor"))
    assert epochs == [1, 2, 3]  # strictly monotone, one mint per loss
    assert survivor.view()["members"] == [0]
    assert survivor.detect() == []  # both publications consumed


def test_membership_dir_exported_to_workers():
    """The store path reaches the training side: assemble_worker_command
    exports ACCELERATE_MEMBERSHIP_DIR so an unmodified script's
    ElasticCoordinator finds the store via MembershipService.from_env."""
    import argparse

    from accelerate_tpu.commands.pod import assemble_worker_command

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, membership_dir="/mnt/gcs/membership",
        training_script="train.py", training_script_args=[],
    )
    command = assemble_worker_command(args)
    assert "export ACCELERATE_MEMBERSHIP_DIR=/mnt/gcs/membership" in command
    # and without the flag nothing leaks
    args.membership_dir = None
    assert "MEMBERSHIP" not in assemble_worker_command(args)


def test_cli_membership_dir_requires_elastic():
    import argparse

    from accelerate_tpu.commands.pod import run

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, num_workers=2, restart_on_failure=0,
        heartbeat_timeout=0.0, elastic=False, membership_dir="/tmp/m",
        training_script="train.py", training_script_args=[],
    )
    with pytest.raises(ValueError, match="elastic"):
        run(args)


def test_supervise_rejects_unknown_partial_failure_mode():
    with pytest.raises(ValueError, match="partial_failure"):
        supervise(lambda i: _FakeProc(0), 1, partial_failure="nope")


def test_cli_elastic_requires_num_workers():
    import argparse

    from accelerate_tpu.commands.pod import run

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, num_workers=None, restart_on_failure=0,
        heartbeat_timeout=0.0, elastic=True, training_script="train.py",
        training_script_args=[],
    )
    with pytest.raises(ValueError, match="num_workers"):
        run(args)


def test_default_restart_policy_is_jittered_backoff():
    from accelerate_tpu.commands.pod import RESTART_POLICY

    assert RESTART_POLICY.base_delay > 0
    assert RESTART_POLICY.jitter > 0
    # delay_for stays within the jitter envelope and under the cap
    for attempt in range(8):
        d = RESTART_POLICY.delay_for(attempt)
        assert 0 < d <= RESTART_POLICY.max_delay * (1 + RESTART_POLICY.jitter)
