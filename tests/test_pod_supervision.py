"""pod-launch supervision: exit propagation, dead-host kill, heartbeat,
restart (VERDICT r4 missing #2 — torchrun-elastic analogue)."""

import subprocess
import sys
import time

import pytest

from accelerate_tpu.commands.pod import supervise


def _spawn_script(scripts):
    """spawn(i) running scripts[i] with `python -c`."""

    def spawn(i):
        return subprocess.Popen(
            [sys.executable, "-u", "-c", scripts[i]],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    return spawn


def test_all_workers_succeed():
    spawn = _spawn_script(["print('a')", "print('b')"])
    assert supervise(spawn, 2, poll_interval=0.05) == 0


def test_failing_worker_propagates_exit_code_and_kills_peers():
    """One dead host must fail the job loudly, not hang the rendezvous."""
    spawn = _spawn_script([
        "import time; time.sleep(60)",   # healthy worker stuck in 'rendezvous'
        "import sys; sys.exit(3)",       # dead host
    ])
    start = time.monotonic()
    assert supervise(spawn, 2, poll_interval=0.05) == 3
    assert time.monotonic() - start < 30  # did NOT wait out the sleeping peer


def test_heartbeat_kills_silent_worker():
    spawn = _spawn_script([
        "import time\nwhile True:\n    print('step', flush=True)\n    time.sleep(0.05)",
        "import time; time.sleep(60)",   # never prints: silent hang
    ])
    start = time.monotonic()
    assert supervise(spawn, 2, heartbeat_timeout=0.5, poll_interval=0.05) == 124
    assert time.monotonic() - start < 30


def test_restart_on_failure_retries_then_succeeds(tmp_path):
    """First attempt fails, relaunch succeeds (state via a marker file)."""
    marker = tmp_path / "attempted"
    script = (
        f"import os, sys\n"
        f"p = {str(marker)!r}\n"
        f"if os.path.exists(p):\n"
        f"    sys.exit(0)\n"
        f"open(p, 'w').close()\n"
        f"sys.exit(7)\n"
    )
    spawn = _spawn_script([script])
    assert supervise(spawn, 1, restarts=2, poll_interval=0.05) == 0
    assert marker.exists()


def test_restarts_exhausted_returns_failure():
    spawn = _spawn_script(["import sys; sys.exit(9)"])
    assert supervise(spawn, 1, restarts=1, poll_interval=0.05) == 9


def test_worker_output_is_prefixed(capfd):
    spawn = _spawn_script(["print('hello-from-zero')"])
    assert supervise(spawn, 1, poll_interval=0.05) == 0
    # pump threads race process exit by a hair
    time.sleep(0.2)
    assert "[worker 0] hello-from-zero" in capfd.readouterr().out


def test_cli_debug_prints_per_worker_commands(capsys):
    import argparse

    from accelerate_tpu.commands.pod import run

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, num_workers=2, restart_on_failure=0,
        heartbeat_timeout=0.0, training_script="train.py", training_script_args=[],
    )
    assert run(args) == 0
    out = capsys.readouterr().out
    assert "--worker 0" in out and "--worker 1" in out


def test_supervision_flags_require_num_workers():
    import argparse

    from accelerate_tpu.commands.pod import run

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, num_workers=None, restart_on_failure=2,
        heartbeat_timeout=0.0, training_script="train.py", training_script_args=[],
    )
    with pytest.raises(ValueError, match="num_workers"):
        run(args)


def test_auto_resume_requires_num_workers():
    import argparse

    from accelerate_tpu.commands.pod import run

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, num_workers=None, restart_on_failure=0,
        heartbeat_timeout=0.0, auto_resume=True, training_script="train.py",
        training_script_args=[],
    )
    with pytest.raises(ValueError, match="num_workers"):
        run(args)


def test_auto_resume_requires_restarts():
    """--auto_resume without --restart_on_failure would silently never
    resume (the job dies on first failure) — reject loudly instead."""
    import argparse

    from accelerate_tpu.commands.pod import run

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, num_workers=2, restart_on_failure=0,
        heartbeat_timeout=0.0, auto_resume=True, training_script="train.py",
        training_script_args=[],
    )
    with pytest.raises(ValueError, match="restart_on_failure"):
        run(args)


def test_assemble_worker_command_resume_appends_flag():
    import argparse

    from accelerate_tpu.commands.pod import assemble_worker_command

    args = argparse.Namespace(
        tpu_name="pod", tpu_zone="z", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, training_script="train.py",
        training_script_args=["--epochs", "3"],
    )
    plain = assemble_worker_command(args)
    resumed = assemble_worker_command(args, resume=True)
    assert plain.endswith("train.py --epochs 3")
    assert resumed.endswith("train.py --epochs 3 --resume auto")


def test_supervise_passes_attempt_to_two_arg_spawn():
    """Relaunch attempts see attempt numbers (the auto-resume hook): the first
    attempt fails, the second — which a real spawn would launch with
    `--resume auto` — succeeds."""
    attempts = []

    def spawn(i, attempt):
        attempts.append((i, attempt))
        code = 5 if attempt == 1 else 0
        return subprocess.Popen(
            [sys.executable, "-c", f"import sys; sys.exit({code})"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    assert supervise(spawn, 1, restarts=1, poll_interval=0.05) == 0
    assert attempts == [(0, 1), (0, 2)]


def test_supervise_single_arg_spawn_still_works():
    spawn = _spawn_script(["print('legacy')"])
    assert supervise(spawn, 1, poll_interval=0.05) == 0
