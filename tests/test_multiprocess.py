"""REAL multi-process distributed tests: two OS processes rendezvous through
jax.distributed on CPU (each with 4 virtual devices → one 8-device global
mesh), launched through the actual `accelerate-tpu launch` CLI — the closest
CI stand-in for a 2-host TPU pod (reference tests/test_multigpu.py:44-49
pattern; SURVEY §4 tier 2)."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_rendezvous_and_training():
    from accelerate_tpu import test_utils

    script = os.path.join(os.path.dirname(test_utils.__file__), "scripts", "multiprocess_script.py")
    port = _free_port()
    num_processes = 2

    procs = []
    for rank in range(num_processes):
        env = dict(os.environ)
        # each process gets its OWN virtual devices (4 local → 8 global);
        # the payload forces the CPU backend through jax.config (a
        # site-installed TPU platform ignores JAX_PLATFORMS)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["JAX_PLATFORMS"] = "cpu"
        env["ACCELERATE_TEST_FORCE_CPU_DEVICES"] = "4"
        env.pop("ACCELERATE_NUM_PROCESSES", None)
        cmd = [
            sys.executable, "-m", "accelerate_tpu.commands.cli", "launch",
            "--num_processes", str(num_processes),
            "--process_id", str(rank),
            "--coordinator_address", f"127.0.0.1:{port}",
            script,
        ]
        procs.append(
            subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        )

    outputs = []
    for rank, proc in enumerate(procs):
        out, _ = proc.communicate(timeout=540)
        outputs.append((rank, proc.returncode, out))
    for rank, rc, out in outputs:
        assert rc == 0, f"process {rank} failed:\n{out}"
    # main process prints the summary line
    assert any('"multiprocess_ok": true' in out for _, _, out in outputs), outputs
