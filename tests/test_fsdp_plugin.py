"""FSDP plugin semantics: ZeRO stages, cpu_offload, activation checkpointing,
adjust_scheduler — every field must change observable behavior
(reference dataclasses.py:997-1216, DeepSpeed ZeRO stages accelerator.py:1486)."""

import numpy as np
import optax

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from accelerate_tpu import (
    Accelerator,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    ParallelismConfig,
)
from accelerate_tpu.models import Llama


class BigLinear:
    """One big weight so the fsdp auto-rule engages (above min_weight_size)."""

    def init(self, rng):
        del rng
        return {"w": jnp.zeros((256, 64), jnp.float32), "b": jnp.zeros((64,), jnp.float32)}

    @staticmethod
    def apply(params, x):
        return x @ params["w"] + params["b"]


def _loss(params, batch):
    out = BigLinear.apply(params, batch["x"])
    return jnp.mean((out - batch["y"]) ** 2)


def _batch(n=16):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 256)).astype(np.float32)
    y = rng.normal(size=(n, 64)).astype(np.float32)
    return {"x": jnp.asarray(x), "y": jnp.asarray(y)}


def test_stage3_shards_params_and_moments():
    plugin = FullyShardedDataParallelPlugin(stage=3, min_weight_size=1024)
    acc = Accelerator(parallelism=ParallelismConfig(fsdp=8), fsdp_plugin=plugin)
    model = acc.prepare(BigLinear())
    opt = acc.prepare_optimizer(optax.adam(1e-3))
    assert model.params_shardings["w"].spec == P("fsdp", None)
    # adam moments mirror the sharded param layout
    mu_sharding = jax.tree.leaves(
        jax.tree.map(lambda s: s, opt._opt_state_shardings), is_leaf=lambda x: hasattr(x, "spec")
    )
    assert any(s.spec == P("fsdp", None) for s in mu_sharding)


def test_stage2_replicates_params_but_shards_moments():
    plugin = FullyShardedDataParallelPlugin(stage=2, min_weight_size=1024)
    acc = Accelerator(parallelism=ParallelismConfig(fsdp=8), fsdp_plugin=plugin)
    model = acc.prepare(BigLinear())
    opt = acc.prepare_optimizer(optax.adam(1e-3))
    # params replicated (ZeRO-2: only grads/opt-state shard)
    assert model.params_shardings["w"].spec == P()
    # moment buffers sharded over fsdp (weight-update sharding)
    moment_specs = [
        s.spec
        for s in jax.tree.leaves(opt._opt_state_shardings, is_leaf=lambda x: hasattr(x, "spec"))
        if hasattr(s, "spec")
    ]
    assert P("fsdp", None) in moment_specs
    # the invariant must SURVIVE stepping: without pinned out_shardings GSPMD
    # propagates the moment sharding into the updated params
    batch = _batch()
    acc.backward(_loss, batch)
    opt.step()
    assert model.params["w"].sharding.spec == P()
    step = acc.compiled_step(_loss)
    step(batch)
    assert model.params["w"].sharding.spec == P()
    # ...and the moment shardings survive too (GSPMD must not wash them out)
    specs_after = {l.sharding.spec for l in jax.tree.leaves(opt.opt_state) if hasattr(l, "sharding")}
    assert specs_after & {P("fsdp"), P("fsdp", None)}


def test_stage2_training_matches_stage3():
    """ZeRO stage is a memory layout, not a math change."""
    results = {}
    for stage in (2, 3):
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        plugin = FullyShardedDataParallelPlugin(stage=stage, min_weight_size=1024)
        acc = Accelerator(parallelism=ParallelismConfig(fsdp=8), fsdp_plugin=plugin)
        model = acc.prepare(BigLinear())
        opt = acc.prepare_optimizer(optax.adam(1e-2))
        batch = _batch()
        for _ in range(5):
            acc.backward(_loss, batch)
            opt.step()
            opt.zero_grad()
        results[stage] = jax.device_get(model.params)
    np.testing.assert_allclose(
        np.asarray(results[2]["w"]), np.asarray(results[3]["w"]), rtol=2e-5, atol=1e-6
    )


def test_cpu_offload_keeps_opt_state_on_host():
    plugin = FullyShardedDataParallelPlugin(stage=3, cpu_offload=True, min_weight_size=1024)
    acc = Accelerator(parallelism=ParallelismConfig(fsdp=8), fsdp_plugin=plugin)
    model = acc.prepare(BigLinear())
    opt = acc.prepare_optimizer(optax.adam(1e-2))
    backend_has_pinned_host = "pinned_host" in {
        m.kind for m in jax.devices()[0].addressable_memories()
    }
    kinds = {
        leaf.sharding.memory_kind
        for leaf in jax.tree.leaves(opt.opt_state)
        if hasattr(leaf, "sharding")
    }
    if backend_has_pinned_host:
        assert "pinned_host" in kinds  # non-scalar state offloaded (scalars stay on device)
    batch = _batch()
    losses = []
    for _ in range(4):
        losses.append(float(acc.backward(_loss, batch)))
        opt.step()
        opt.zero_grad()
    assert losses[-1] < losses[0]
    # state returned to host after stepping
    if backend_has_pinned_host:
        kinds_after = {
            leaf.sharding.memory_kind
            for leaf in jax.tree.leaves(opt.opt_state)
            if hasattr(leaf, "sharding")
        }
        assert "pinned_host" in kinds_after


def test_activation_checkpointing_sets_remat_policy():
    plugin = FullyShardedDataParallelPlugin(activation_checkpointing=True)
    acc = Accelerator(parallelism=ParallelismConfig(fsdp=8), fsdp_plugin=plugin)
    # full recompute except the named flash out/lse (identical to "full" on
    # paths that never hit the flash kernel)
    assert acc.compilation_config.remat_policy == "save_flash"
    assert acc.compilation_config.checkpoint_policy() is not None
    # and training still runs through the remat path
    model = acc.prepare(BigLinear())
    opt = acc.prepare_optimizer(optax.adam(1e-2))
    batch = _batch()
    loss = acc.backward(_loss, batch)
    opt.step()
    assert np.isfinite(float(loss))


def test_adjust_scheduler_advances_on_accumulation_steps():
    from accelerate_tpu.scheduler import AcceleratedScheduler

    for adjust, expected_extra in ((True, 3), (False, 0)):
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        acc = Accelerator(
            gradient_accumulation_plugin=GradientAccumulationPlugin(
                num_steps=4, adjust_scheduler=adjust, sync_with_dataloader=False
            )
        )
        model = acc.prepare(BigLinear())
        opt = acc.prepare_optimizer(optax.sgd(0.1))
        sched = AcceleratedScheduler(lambda c: 1.0 / (1 + c), optimizer=opt)
        batch = _batch()
        for _ in range(4):  # one full accumulation window
            with acc.accumulate(model):
                acc.backward(_loss, batch)
                opt.step()
                sched.step()
                opt.zero_grad()
        data_extent = 8  # default mesh: all devices on the data axis
        assert sched.step_count == expected_extra + data_extent


def test_activation_checkpointing_uses_per_layer_remat_for_scan_models():
    """Scan-structured models remat per layer (attention internals recomputed,
    not saved) and the post-step parameters — i.e. the gradients — match the
    no-remat run exactly."""
    import jax.numpy as jnp

    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    results = {}
    for ckpt in (False, True):
        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()
        from accelerate_tpu.utils import set_seed

        set_seed(0)
        plugin = FullyShardedDataParallelPlugin(stage=3, activation_checkpointing=ckpt)
        acc = Accelerator(parallelism=ParallelismConfig(fsdp=8), fsdp_plugin=plugin)
        model = Llama("llama-tiny")
        prepared = acc.prepare(model)
        if ckpt:
            assert callable(model.remat_layers)  # the policy threads through
        else:
            assert model.remat_layers is False
        import optax

        acc.prepare_optimizer(optax.sgd(0.1))
        opt = acc._optimizers[-1]
        batch = {"x": jnp.arange(32, dtype=jnp.int32).reshape(2, 16) % 100}
        acc.backward(lambda p, b: Llama.loss_fn(model)(p, {"input_ids": b["x"]}), batch)
        opt.step()
        results[ckpt] = jax.device_get(prepared.params)
    for got, want in zip(jax.tree.leaves(results[True]), jax.tree.leaves(results[False])):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-7)


def test_pipeline_models_keep_outer_remat_wrap():
    """Pipeline bypasses the layer scan, so activation checkpointing must fall
    back to the outer loss-fn wrap — not silently disappear."""
    plugin = FullyShardedDataParallelPlugin(activation_checkpointing=True)
    acc = Accelerator(parallelism=ParallelismConfig(fsdp=2, pipeline=2, tensor=2), fsdp_plugin=plugin)
    model = Llama("llama-tiny")
    prepared = acc.prepare_model(model)
    assert model.pipeline_fn is not None
    assert model.remat_layers is False
    assert acc._effective_remat_policy(prepared) is not None


def test_reprepare_without_checkpointing_resets_remat():
    """remat_layers must not leak across Accelerator configs sharing a model."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    model = Llama("llama-tiny")
    plugin = FullyShardedDataParallelPlugin(activation_checkpointing=True)
    acc = Accelerator(parallelism=ParallelismConfig(fsdp=8), fsdp_plugin=plugin)
    acc.prepare_model(model)
    assert callable(model.remat_layers)
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc2 = Accelerator(parallelism=ParallelismConfig(fsdp=8))
    acc2.prepare_model(model)
    assert model.remat_layers is False


def test_stage2_llama_with_tp_keeps_tp_sharding():
    """Stage 1/2 must not strip the explicit TP rules, only the fsdp fold."""
    plugin = FullyShardedDataParallelPlugin(stage=2, min_weight_size=0)
    acc = Accelerator(parallelism=ParallelismConfig(fsdp=2, tensor=2), fsdp_plugin=plugin)
    model = Llama("llama-tiny")
    prepared = acc.prepare_model(model)
    wq_spec = prepared.params_shardings["layers"]["wq"].spec
    # TP axis present, fsdp axis absent from the param layout
    flat = [ax for axes in wq_spec if axes is not None for ax in (axes if isinstance(axes, tuple) else (axes,))]
    assert "tensor" in flat
    assert "fsdp" not in flat


def test_reprepare_without_pipeline_clears_stale_pipeline_fn():
    """A pipeline_fn built on an old mesh must not survive re-preparation."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    model = Llama("llama-tiny")
    acc = Accelerator(parallelism=ParallelismConfig(fsdp=2, pipeline=2, tensor=2))
    acc.prepare_model(model)
    assert model.pipeline_fn is not None
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    plugin = FullyShardedDataParallelPlugin(activation_checkpointing=True)
    acc2 = Accelerator(parallelism=ParallelismConfig(fsdp=8), fsdp_plugin=plugin)
    acc2.prepare_model(model)
    assert model.pipeline_fn is None
    assert callable(model.remat_layers)  # per-layer remat re-engages
