"""Telemetry subsystem (ISSUE 2 tentpole): step-timer sampling cadence,
compile tracking, MFU math, memory watermarks, goodput across a simulated
SIGTERM save/resume, multi-host aggregation semantics, the profile() /
`accelerate-tpu profile` satellites, and the end-to-end telemetry.jsonl demo
(the acceptance-criteria smoke test — fast, tier-1)."""

import json
import logging
import os

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, CheckpointManager, Telemetry, TelemetryConfig
from accelerate_tpu.models.config import get_config, param_count, train_flops_per_step
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.telemetry import CompileTracker, GoodputTracker, StepTimer
from accelerate_tpu.telemetry.profiler import ProfileWindow


class Tiny:
    def init(self, rng):
        return {"w": jax.random.normal(rng, (8, 4), jnp.float32)}

    @staticmethod
    def apply(params, x):
        return x @ params["w"]


def _loss(params, batch):
    return jnp.mean(Tiny.apply(params, batch) ** 2)


def _reset_singletons():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


# ---------------------------------------------------------------------------
# step timer
# ---------------------------------------------------------------------------


def test_step_timer_fences_only_on_sampling_cadence():
    timer = StepTimer(sample_every=8)
    x = jnp.ones(())
    for _ in range(33):
        timer.step(x)
    # 33 steps at cadence 8 → boundaries at 8,16,24,32: exactly 4 fences
    assert timer.fence_count == 4
    # first boundary only sets the baseline: 3 completed windows
    assert len(timer.samples) == 3
    assert timer.steps == 33
    summary = timer.summary()
    assert summary["sampled_windows"] == 3
    assert summary["step_time_p50_ms"] > 0
    assert summary["steps_per_sec"] > 0


def test_step_timer_discard_window_drops_stall():
    timer = StepTimer(sample_every=2)
    x = jnp.ones(())
    for _ in range(4):
        timer.step(x)
    n = len(timer.samples)
    timer.discard_window()  # e.g. a checkpoint save happened here
    for _ in range(2):
        timer.step(x)
    # the window spanning the discard contributes no sample
    assert len(timer.samples) == n
    for _ in range(2):
        timer.step(x)
    assert len(timer.samples) == n + 1


def test_step_timer_rejects_bad_cadence():
    with pytest.raises(ValueError):
        StepTimer(sample_every=0)


# ---------------------------------------------------------------------------
# FLOPs / MFU math
# ---------------------------------------------------------------------------


def test_train_flops_matches_hand_computation():
    cfg = get_config("llama-tiny")
    seq, batch = 64, 4
    by_hand = batch * seq * (
        6.0 * param_count(cfg) + 12.0 * cfg.num_layers * cfg.hidden_size * seq
    )
    assert train_flops_per_step(cfg, batch, seq) == by_hand


def test_mfu_derivation_against_hand_computed_flops():
    _reset_singletons()
    acc = Accelerator(telemetry_config=TelemetryConfig(sample_every=4))
    telemetry = acc.telemetry
    cfg = get_config("llama-tiny")
    peak = 1e12
    telemetry.configure_throughput(cfg, batch_size=8, seq_len=32, peak_flops_per_device=peak)
    # inject a known step time: 10 ms/step
    telemetry.timer._record(0.1, 10)
    telemetry.timer.steps = 10
    metrics = telemetry.metrics()
    flops = train_flops_per_step(cfg, 8, 32)
    expected_mfu = flops * 100.0 / (peak * jax.device_count())
    assert metrics["mfu"] == pytest.approx(expected_mfu)
    assert metrics["tokens_per_sec"] == pytest.approx(8 * 32 * 100.0)
    assert metrics["examples_per_sec"] == pytest.approx(8 * 100.0)


# ---------------------------------------------------------------------------
# compile tracking
# ---------------------------------------------------------------------------


def test_compile_tracker_counts_real_compiles_and_cache_events():
    from accelerate_tpu.utils.jit_cache import dot_keyed_jit

    with CompileTracker() as tracker:
        f = jax.jit(lambda x: x * 3 + 1)
        f(jnp.ones(7))   # compile
        f(jnp.ones(7))   # cached
        f(jnp.ones(11))  # new shape → compile

        class Owner:
            pass

        owner = Owner()
        dot_keyed_jit(owner, "_cache", "k", lambda: "built")  # miss
        dot_keyed_jit(owner, "_cache", "k", lambda: "built")  # hit
    snap = tracker.snapshot()
    assert snap["compile_count"] >= 2
    assert snap["compile_seconds"] > 0
    assert snap["jit_cache_misses"] == 1
    assert snap["jit_cache_hits"] == 1
    # stopped tracker stops accumulating
    f(jnp.ones(13))
    assert tracker.snapshot()["compile_count"] == snap["compile_count"]


# ---------------------------------------------------------------------------
# goodput across a simulated SIGTERM save/resume
# ---------------------------------------------------------------------------


def test_goodput_bookkeeping_across_preemption_save_and_resume(tmp_path):
    acc = Accelerator(telemetry_config=TelemetryConfig(sample_every=2, dir=str(tmp_path)))
    acc.prepare(Tiny())
    opt = acc.prepare_optimizer(optax.sgd(1e-2))
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path / "ckpts"), handle_signals=())
    batch = jnp.ones((4, 8), jnp.float32)
    for _ in range(4):
        loss = acc.backward(_loss, batch)
        opt.step()
        opt.zero_grad()
        acc.telemetry.step(loss)
    manager.request_preemption()  # simulated SIGTERM (handler just flips this flag)
    assert manager.should_save(4)
    manager.save(4)
    assert manager.exit_requested
    saved = acc.telemetry.goodput._lost
    assert saved.get("checkpoint_save", 0) > 0
    assert acc.telemetry.goodput._counts["checkpoint_save"] == 1

    # "restart": fresh singletons + accelerator, as the relaunched process has
    _reset_singletons()
    acc2 = Accelerator(telemetry_config=TelemetryConfig(sample_every=2, dir=str(tmp_path)))
    acc2.prepare(Tiny())
    opt2 = acc2.prepare_optimizer(optax.sgd(1e-2))
    manager2 = CheckpointManager(acc2, checkpoint_dir=str(tmp_path / "ckpts"), handle_signals=())
    resume = manager2.resume("auto")
    assert resume is not None and resume.step == 4
    assert acc2.telemetry.goodput.restarts == 1
    assert acc2.telemetry.goodput._lost.get("checkpoint_restore", 0) > 0
    for _ in range(4):
        loss = acc2.backward(_loss, batch)
        opt2.step()
        opt2.zero_grad()
        acc2.telemetry.step(loss)
    record = acc2.telemetry.flush()
    goodput = record["goodput"]
    assert goodput["restarts"] == 1
    assert goodput["overhead_s"]["checkpoint_restore"] > 0
    assert goodput["lost_s"] > 0
    assert 0 < goodput["goodput"] <= 1


def test_goodput_tracker_ledger_math():
    tracker = GoodputTracker()
    tracker.record("checkpoint_save", 2.0)
    tracker.record("checkpoint_save", 1.0)
    with tracker.timer("dataloader_rewind"):
        pass
    snap = tracker.snapshot(productive_seconds=12.0, compile_seconds=3.0)
    # compile came only from monitoring → added on top of the ledger
    assert snap["lost_s"] == pytest.approx(3.0 + 3.0, abs=0.1)
    assert snap["goodput"] == pytest.approx(12.0 / (12.0 + snap["lost_s"]), abs=1e-4)
    assert snap["event_counts"]["checkpoint_save"] == 2


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------


def test_aggregate_metrics_single_process_identity():
    state = PartialState()
    agg = state.aggregate_metrics({"a": 2.0, "b": 3, "skip": "str", "flag": True})
    assert agg["a"] == {"min": 2.0, "max": 2.0, "mean": 2.0}
    assert agg["b"] == {"min": 3.0, "max": 3.0, "mean": 3.0}
    assert "skip" not in agg and "flag" not in agg


# ---------------------------------------------------------------------------
# the acceptance demo: CPU-backend end-to-end telemetry.jsonl
# ---------------------------------------------------------------------------


def test_telemetry_jsonl_end_to_end_with_save_resume(tmp_path):
    """The ISSUE acceptance criterion: a CPU run produces telemetry.jsonl with
    step_time percentiles, compile events, memory watermarks, tokens/sec, MFU,
    and a goodput ratio after a simulated save/resume — with zero forced
    fences outside the sampling cadence."""
    sample_every = 4
    config = TelemetryConfig(sample_every=sample_every, dir=str(tmp_path))
    acc = Accelerator(telemetry_config=config)
    acc.prepare(Tiny())
    acc.prepare_optimizer(optax.sgd(1e-2))
    step = acc.compiled_step(_loss)
    cfg = get_config("llama-tiny")
    acc.telemetry.configure_throughput(
        cfg, batch_size=16, seq_len=8, peak_flops_per_device=1e12
    )
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path / "ckpts"), handle_signals=())
    batch = jnp.ones((16, 8), jnp.float32)
    for _ in range(8):
        loss = step(batch)
        acc.telemetry.step(loss)
    manager.request_preemption()
    manager.save(8)

    _reset_singletons()
    acc2 = Accelerator(telemetry_config=config)
    acc2.prepare(Tiny())
    acc2.prepare_optimizer(optax.sgd(1e-2))
    step2 = acc2.compiled_step(_loss)
    acc2.telemetry.configure_throughput(
        cfg, batch_size=16, seq_len=8, peak_flops_per_device=1e12
    )
    manager2 = CheckpointManager(acc2, checkpoint_dir=str(tmp_path / "ckpts"), handle_signals=())
    assert manager2.resume("auto").step == 8
    n_steps = 16
    for _ in range(n_steps):
        loss = step2(batch)
        acc2.telemetry.step(loss)
    # zero forced sync outside the cadence: one fence per completed boundary
    assert acc2.telemetry.timer.fence_count == n_steps // sample_every
    acc2.telemetry.finish()

    records = [json.loads(l) for l in open(tmp_path / "telemetry.jsonl")]
    record = records[-1]
    metrics = record["metrics"]
    for key in (
        "step_time_p50_ms",
        "step_time_p90_ms",
        "step_time_p99_ms",
        "steps_per_sec",
        "tokens_per_sec",
        "mfu",
        "compile_count",
        "goodput",
    ):
        assert key in metrics, f"missing {key} in {sorted(metrics)}"
    assert metrics["compile_count"] > 0, "compile events not captured"
    assert record["compiles"]["events"], "per-event compile detail missing"
    # memory watermarks: device stats on TPU, host RSS watermark on CPU
    assert record["memory"].get("host_peak_rss_bytes") or record["memory"].get(
        "hbm_high_watermark_bytes"
    )
    assert record["goodput"]["restarts"] == 1
    assert record["goodput"]["overhead_s"]["checkpoint_restore"] > 0
    assert 0 < metrics["goodput"] <= 1
    assert metrics["mfu"] > 0
    assert record["aggregate"]["steps"]["mean"] == n_steps
    assert metrics["optimizer_steps"] == n_steps


# ---------------------------------------------------------------------------
# satellites: profile(), JSONL tracker, rank-aware logging, profile CLI
# ---------------------------------------------------------------------------


def test_profile_is_reentrancy_safe_and_snapshots_memory(tmp_path):
    acc = Accelerator()
    with acc.profile(str(tmp_path / "trace"), host_metadata={"run": "t1"}) as capture:
        with pytest.raises(RuntimeError, match="already active"):
            with acc.profile(str(tmp_path / "nested")):
                pass
        (jnp.ones((4, 4)) @ jnp.ones((4, 4))).block_until_ready()
    # still a str (os.walk call sites keep working) with snapshot attributes
    assert isinstance(capture, str) and capture == str(tmp_path / "trace")
    assert isinstance(capture.memory_before, list)
    assert isinstance(capture.memory_after, list)
    meta = json.load(open(tmp_path / "trace" / "host_metadata.json"))
    assert meta["run"] == "t1" and meta["process_index"] == 0
    # the guard releases: profiling again works
    with acc.profile(str(tmp_path / "trace2")):
        pass


def test_jsonl_tracker_coerces_scalars_and_fsyncs(tmp_path):
    from accelerate_tpu.tracking import JSONLTracker

    tracker = JSONLTracker("run", logging_dir=str(tmp_path))
    tracker.log(
        {
            "jax_scalar": jnp.float32(1.5),
            "np_scalar": np.float64(2.5),
            "np_int": np.int64(7),
            "arr": np.arange(3),
            "weird": {("a", "b"): 1},  # tuple key: unserializable structure
        },
        step=0,
    )
    tracker.finish()
    tracker.finish()  # double-finish must not raise
    [line] = [json.loads(l) for l in open(tmp_path / "run" / "metrics.jsonl")]
    assert line["jax_scalar"] == 1.5  # a NUMBER, not the string "1.5"
    assert line["np_scalar"] == 2.5
    assert line["np_int"] == 7
    assert line["arr"] == [0, 1, 2]
    assert line["weird"] == {"('a', 'b')": 1}


def test_logging_stamps_process_index():
    from accelerate_tpu.logging import get_logger

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = get_logger("telemetry_rank_test", log_level="INFO")
    handler = Capture()
    logger.logger.addHandler(handler)
    try:
        logger.info("hello")
        logger.info("everyone", main_process_only=False)
    finally:
        logger.logger.removeHandler(handler)
    assert len(records) == 2
    for record in records:
        assert record.process_index == 0
        assert record.local_process_index == 0
    # formatters can surface the stamp
    fmt = logging.Formatter("[rank %(process_index)s] %(message)s")
    assert fmt.format(records[0]) == "[rank 0] hello"


def test_profile_cli_builds_window_env(tmp_path):
    import argparse

    from accelerate_tpu.commands import profile as profile_cmd

    parser = argparse.ArgumentParser()
    sub = parser.add_subparsers()
    profile_cmd.register_subcommand(sub)
    args = parser.parse_args(
        [
            "profile", "--output-dir", str(tmp_path), "--start-step", "100",
            "--num-steps", "20", "--port", "9999", "train.py", "--epochs", "1",
        ]
    )
    env = profile_cmd.build_env(args)
    assert env["ACCELERATE_PROFILE_DIR"] == str(tmp_path)
    assert env["ACCELERATE_PROFILE_START_STEP"] == "100"
    assert env["ACCELERATE_PROFILE_STEPS"] == "20"
    assert env["ACCELERATE_PROFILE_PORT"] == "9999"
    assert args.training_script == "train.py"
    assert args.training_script_args == ["--epochs", "1"]


def test_profile_window_env_arming_and_step_boundaries(tmp_path, monkeypatch):
    monkeypatch.setenv("ACCELERATE_PROFILE_DIR", str(tmp_path))
    monkeypatch.setenv("ACCELERATE_PROFILE_START_STEP", "3")
    monkeypatch.setenv("ACCELERATE_PROFILE_STEPS", "2")
    window = ProfileWindow.from_env()
    assert window is not None and window.armed
    started, stopped = [], []
    monkeypatch.setattr(window, "_start", lambda: (started.append(True), setattr(window, "active", True)))

    def stop():
        stopped.append(True)
        window.active = False
        window.completed = True

    monkeypatch.setattr(window, "_stop", stop)
    for step in range(8):
        window.on_step(step)
    assert len(started) == 1 and len(stopped) == 1
    assert not window.armed  # one-shot: never rearms


def test_profile_window_writes_real_trace(tmp_path):
    window = ProfileWindow(output_dir=str(tmp_path), start_step=1, num_steps=2)
    for step in range(5):
        (jnp.ones((4, 4)) * step).block_until_ready()
        window.on_step(step)
    assert window.completed
    trace_dir = os.path.join(str(tmp_path), "host_0")
    found = [os.path.join(r, f) for r, _, fs in os.walk(trace_dir) for f in fs]
    assert found, "profiler window produced no trace files"


def test_flush_every_and_canonical_loop_emit_one_record_per_boundary(tmp_path):
    acc = Accelerator(
        telemetry_config=TelemetryConfig(sample_every=2, flush_every=4, dir=str(tmp_path))
    )
    telemetry = acc.telemetry
    x = jnp.ones(())
    for _ in range(8):
        telemetry.step(x)
        if telemetry.should_flush():  # the hub docstring's canonical loop
            telemetry.flush(step=telemetry.steps)
    telemetry.finish(flush=False)
    telemetry.finish(flush=False)  # idempotent
    records = [json.loads(l) for l in open(tmp_path / "telemetry.jsonl")]
    # auto-flush and should_flush() compose: exactly one record per boundary
    assert [r["step"] for r in records] == [4, 8]


def test_finish_is_idempotent_no_duplicate_final_record(tmp_path):
    acc = Accelerator(telemetry_config=TelemetryConfig(sample_every=2, dir=str(tmp_path)))
    for _ in range(4):
        acc.telemetry.step(jnp.ones(()))
    acc.telemetry.finish()
    acc.end_training()  # calls finish() again — must be a no-op
    records = [json.loads(l) for l in open(tmp_path / "telemetry.jsonl")]
    assert len(records) == 1


def test_telemetry_disabled_is_inert(tmp_path):
    acc = Accelerator(telemetry_config=TelemetryConfig(enabled=False, dir=str(tmp_path)))
    acc.telemetry.step(jnp.ones(()))
    assert acc.telemetry.flush() is None
    acc.telemetry.finish()
    assert acc.telemetry.timer.steps == 0
    assert not os.path.exists(tmp_path / "telemetry.jsonl")
