"""Pipeline-parallel tests: forward parity, training, hybrid meshes."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import Llama
from accelerate_tpu.state import PartialState


def _fresh_model(seed=0):
    model = Llama("llama-tiny")  # 2 layers
    params = model.init(jax.random.key(seed))
    return model, params


def test_pipeline_forward_matches_single_device():
    model, params = _fresh_model()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (8, 16)), jnp.int32)
    expected = model.apply(params, ids)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    assert model.pipeline_fn is not None
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_pipeline_params_sharded_over_pipeline_axis():
    model, params = _fresh_model()
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    spec = prepared.params["layers"]["wq"].sharding.spec
    assert spec[0] == "pipeline"


def test_pipeline_with_tp_forward_matches():
    model, params = _fresh_model(seed=1)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 1024, (8, 16)), jnp.int32)
    expected = model.apply(params, ids)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, tensor=2))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_pipeline_training_converges():
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, data=4))
    model = Llama("llama-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    loss_fn = Llama.loss_fn(model)
    batch = {"input_ids": jnp.asarray(np.random.default_rng(0).integers(0, 1024, (8, 32)), jnp.int32)}
    losses = []
    for _ in range(6):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_pipeline_with_attention_mask_matches():
    """Padded batches must survive the pipeline (masks hop with activations)."""
    model, params = _fresh_model(seed=2)
    ids = jnp.asarray(np.random.default_rng(2).integers(0, 1024, (8, 16)), jnp.int32)
    am = np.ones((8, 16), np.int32)
    am[0, :4] = 0
    am[5, :7] = 0
    am = jnp.asarray(am)
    expected = model.apply(params, ids, attention_mask=am)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(ids, attention_mask=am)
    real = np.asarray(am, bool)
    np.testing.assert_allclose(np.asarray(expected)[real], np.asarray(got)[real], atol=2e-4)


def test_pipeline_rejects_indivisible_layers():
    from accelerate_tpu.parallel.pipeline import make_pipeline_layers_fn
    from accelerate_tpu.models import get_config

    state = PartialState(parallelism=ParallelismConfig(pipeline=8))
    cfg = get_config("llama-tiny")  # 2 layers, pipeline 8
    with pytest.raises(ValueError, match="must divide"):
        make_pipeline_layers_fn(
            cfg, state.mesh, num_microbatches=4, layer_fn=Llama(cfg).pipeline_layer
        )

def test_pipeline_bf16_full_step_with_tp_fsdp():
    """Regression: bf16 + pipeline (the driver dryrun config) used to crash XLA's
    AllReducePromotion pass via low-precision psums emitted from the manual
    shard_map region (pipeline.py). Run the fused compiled_step end-to-end."""
    accelerator = Accelerator(
        mixed_precision="bf16",
        gradient_accumulation_steps=2,
        parallelism=ParallelismConfig(fsdp=2, pipeline=2, tensor=2),
    )
    model = Llama("llama-tiny")
    accelerator.prepare_model(model)
    accelerator.prepare_optimizer(optax.adamw(1e-3))
    step = accelerator.compiled_step(Llama.loss_fn(model), clip_grad_norm=1.0)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (16, 32)), jnp.int32)
    batch = {"input_ids": jax.device_put(ids, accelerator.state.data_sharding())}
    losses = [float(step(batch)) for _ in range(3)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_pipeline_bf16_forward_matches_single_device():
    """bf16 pipeline forward must agree with the bf16 single-device forward."""
    model, params = _fresh_model(seed=3)
    params16 = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 1024, (8, 16)), jnp.int32)
    expected = model.apply(params16, ids)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params16)
    got = prepared(ids)
    np.testing.assert_allclose(
        np.asarray(expected, np.float32), np.asarray(got, np.float32), atol=1.5e-1
    )


# -- interleaved virtual stages (Megatron num_layers_per_virtual_pipeline_stage) --


def _fresh_4layer_model(seed=0):
    import dataclasses

    from accelerate_tpu.models import get_config

    cfg = dataclasses.replace(get_config("llama-tiny"), num_layers=4)
    model = Llama(cfg)
    params = model.init(jax.random.key(seed))
    return model, params


def test_virtual_stages_forward_matches_single_device():
    from accelerate_tpu.utils import ModelParallelPlugin

    model, params = _fresh_4layer_model(seed=4)
    ids = jnp.asarray(np.random.default_rng(4).integers(0, 1024, (8, 16)), jnp.int32)
    expected = model.apply(params, ids)
    model.pipeline_fn = None

    accelerator = Accelerator(
        parallelism=ParallelismConfig(pipeline=2),
        model_parallel_plugin=ModelParallelPlugin(pipeline_size=2, virtual_pipeline_stages=2),
    )
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_virtual_stages_grads_match_gpipe():
    """Same math: grads through the interleaved schedule == v=1 schedule."""
    from accelerate_tpu.parallel.pipeline import make_pipeline_layers_fn
    from accelerate_tpu.models.attention import rotary_embedding

    state = PartialState(parallelism=ParallelismConfig(pipeline=2))
    model, params = _fresh_4layer_model(seed=5)
    cfg = model.config
    ids = jnp.asarray(np.random.default_rng(5).integers(0, 1024, (4, 8)), jnp.int32)
    h = jnp.take(params["embed_tokens"], ids, axis=0)
    cos, sin = rotary_embedding(jnp.arange(8)[None, :], cfg.dim_per_head, cfg.rope_theta)

    def loss(layers, fn):
        out, _ = fn(layers, h, None, cos, sin)
        return (out.astype(jnp.float32) ** 2).mean()

    grads = {}
    for v in (1, 2):
        fn = make_pipeline_layers_fn(
            cfg, state.mesh, num_microbatches=4,
            layer_fn=model.pipeline_layer, virtual_stages=v,
        )
        grads[v] = jax.jit(jax.grad(lambda l: loss(l, fn)))(params["layers"])
    for g1, g2 in zip(jax.tree.leaves(grads[1]), jax.tree.leaves(grads[2])):
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-5)


def test_virtual_stages_bf16_full_step():
    """The driver dryrun config plus interleaving: fused step stays finite."""
    from accelerate_tpu.utils import ModelParallelPlugin

    accelerator = Accelerator(
        mixed_precision="bf16",
        gradient_accumulation_steps=2,
        parallelism=ParallelismConfig(fsdp=2, pipeline=2),
        model_parallel_plugin=ModelParallelPlugin(pipeline_size=2, virtual_pipeline_stages=2),
    )
    model, _ = _fresh_4layer_model(seed=6)
    accelerator.prepare_model(model)
    accelerator.prepare_optimizer(optax.adamw(1e-3))
    step = accelerator.compiled_step(Llama.loss_fn(model), clip_grad_norm=1.0)
    ids = jnp.asarray(np.random.default_rng(6).integers(0, 1024, (16, 32)), jnp.int32)
    batch = {"input_ids": jax.device_put(ids, accelerator.state.data_sharding())}
    losses = [float(step(batch)) for _ in range(3)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_virtual_stages_reject_indivisible():
    from accelerate_tpu.parallel.pipeline import make_pipeline_layers_fn
    from accelerate_tpu.models import get_config

    state = PartialState(parallelism=ParallelismConfig(pipeline=2))
    cfg = get_config("llama-tiny")  # 2 layers: v=2 x P=2 = 4 does not divide
    with pytest.raises(ValueError, match="must divide"):
        make_pipeline_layers_fn(
            cfg, state.mesh, num_microbatches=4,
            layer_fn=Llama(cfg).pipeline_layer, virtual_stages=2,
        )


def test_interleaved_schedule_reduces_idle():
    from accelerate_tpu.parallel.pipeline import build_interleaved_schedule

    *_, idle_v1 = build_interleaved_schedule(4, 1, 8)
    *_, idle_v2 = build_interleaved_schedule(4, 2, 8)
    assert idle_v2 < idle_v1


def test_declared_bcast_const_with_batchlike_leading_dim():
    """ADVICE r4: a batch-invariant const whose leading dim coincidentally
    equals the batch must not be sliced per microbatch when the model
    declares it "bcast"."""
    from accelerate_tpu.parallel.pipeline import make_pipeline_layers_fn
    from accelerate_tpu.models.attention import rotary_embedding

    state = PartialState(parallelism=ParallelismConfig(pipeline=2))
    model, params = _fresh_4layer_model(seed=11)
    cfg = model.config
    b = 4
    ids = jnp.asarray(np.random.default_rng(11).integers(0, 1024, (b, b)), jnp.int32)
    h = jnp.take(params["embed_tokens"], ids, axis=0)
    # seq == batch: cos/sin are [S=b, D/2] — the shape heuristic would slice
    # them per microbatch and feed wrong positions
    cos, sin = rotary_embedding(jnp.arange(b), cfg.dim_per_head, cfg.rope_theta)
    assert cos.shape[0] == b

    expected_h = h
    for i in range(cfg.num_layers):
        lp = jax.tree.map(lambda x: x[i], params["layers"])
        expected_h, _ = model.pipeline_layer(lp, expected_h, None, None, cos, sin, None)

    fn = make_pipeline_layers_fn(
        cfg, state.mesh, num_microbatches=2, layer_fn=model.pipeline_layer,
        const_kinds=("mb", "bcast", "bcast", "mb"),
    )
    got, _ = jax.jit(fn)(params["layers"], h, None, cos, sin, None)
    np.testing.assert_allclose(np.asarray(expected_h), np.asarray(got), atol=1e-5)

    # declared count must match the call
    with pytest.raises(ValueError, match="side inputs"):
        jax.jit(make_pipeline_layers_fn(
            cfg, state.mesh, num_microbatches=2, layer_fn=model.pipeline_layer,
            const_kinds=("mb",),
        ))(params["layers"], h, None, cos, sin, None)
