"""Pipeline-parallel tests: forward parity, training, hybrid meshes."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import Llama
from accelerate_tpu.state import PartialState


def _fresh_model(seed=0):
    model = Llama("llama-tiny")  # 2 layers
    params = model.init(jax.random.key(seed))
    return model, params


def test_pipeline_forward_matches_single_device():
    model, params = _fresh_model()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (8, 16)), jnp.int32)
    expected = model.apply(params, ids)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    assert model.pipeline_fn is not None
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_pipeline_params_sharded_over_pipeline_axis():
    model, params = _fresh_model()
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    spec = prepared.params["layers"]["wq"].sharding.spec
    assert spec[0] == "pipeline"


def test_pipeline_with_tp_forward_matches():
    model, params = _fresh_model(seed=1)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 1024, (8, 16)), jnp.int32)
    expected = model.apply(params, ids)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, tensor=2))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_pipeline_training_converges():
    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2, data=4))
    model = Llama("llama-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    loss_fn = Llama.loss_fn(model)
    batch = {"input_ids": jnp.asarray(np.random.default_rng(0).integers(0, 1024, (8, 32)), jnp.int32)}
    losses = []
    for _ in range(6):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_pipeline_with_attention_mask_matches():
    """Padded batches must survive the pipeline (masks hop with activations)."""
    model, params = _fresh_model(seed=2)
    ids = jnp.asarray(np.random.default_rng(2).integers(0, 1024, (8, 16)), jnp.int32)
    am = np.ones((8, 16), np.int32)
    am[0, :4] = 0
    am[5, :7] = 0
    am = jnp.asarray(am)
    expected = model.apply(params, ids, attention_mask=am)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(ids, attention_mask=am)
    real = np.asarray(am, bool)
    np.testing.assert_allclose(np.asarray(expected)[real], np.asarray(got)[real], atol=2e-4)


def test_pipeline_rejects_indivisible_layers():
    from accelerate_tpu.parallel.pipeline import make_pipeline_layers_fn
    from accelerate_tpu.models import get_config

    state = PartialState(parallelism=ParallelismConfig(pipeline=8))
    cfg = get_config("llama-tiny")  # 2 layers, pipeline 8
    with pytest.raises(ValueError, match="must divide"):
        make_pipeline_layers_fn(cfg, state.mesh, num_microbatches=4)

def test_pipeline_bf16_full_step_with_tp_fsdp():
    """Regression: bf16 + pipeline (the driver dryrun config) used to crash XLA's
    AllReducePromotion pass via low-precision psums emitted from the manual
    shard_map region (pipeline.py). Run the fused compiled_step end-to-end."""
    accelerator = Accelerator(
        mixed_precision="bf16",
        gradient_accumulation_steps=2,
        parallelism=ParallelismConfig(fsdp=2, pipeline=2, tensor=2),
    )
    model = Llama("llama-tiny")
    accelerator.prepare_model(model)
    accelerator.prepare_optimizer(optax.adamw(1e-3))
    step = accelerator.compiled_step(Llama.loss_fn(model), clip_grad_norm=1.0)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (16, 32)), jnp.int32)
    batch = {"input_ids": jax.device_put(ids, accelerator.state.data_sharding())}
    losses = [float(step(batch)) for _ in range(3)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_pipeline_bf16_forward_matches_single_device():
    """bf16 pipeline forward must agree with the bf16 single-device forward."""
    model, params = _fresh_model(seed=3)
    params16 = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 1024, (8, 16)), jnp.int32)
    expected = model.apply(params16, ids)
    model.pipeline_fn = None

    accelerator = Accelerator(parallelism=ParallelismConfig(pipeline=2))
    prepared = accelerator.prepare_model(model, params=params16)
    got = prepared(ids)
    np.testing.assert_allclose(
        np.asarray(expected, np.float32), np.asarray(got, np.float32), atol=1.5e-1
    )
