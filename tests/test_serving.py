"""Continuous-batching serving engine: slot allocator, bucketed prefill,
zero steady-state recompiles, and bit-exactness against sequential generate.

All tier-1-fast on the CPU mesh — the engine's shapes never depend on the
backend, so the compile/jit-cache invariants proven here are the TPU ones.
"""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import GPT2, Llama
from accelerate_tpu.models.generation import generate
from accelerate_tpu.serving import (
    QueueFull,
    ServingEngine,
    SlotAllocator,
    bucket_for,
    kv_cache_bytes,
    params_from_streamed,
    prefill_buckets,
    run_offered_load,
)
from accelerate_tpu.telemetry import CompileTracker


@pytest.fixture(scope="module")
def llama():
    model = Llama("llama-tiny")
    return model, model.init(jax.random.key(0))


@pytest.fixture(scope="module")
def gpt2():
    model = GPT2("gpt2-tiny")
    return model, model.init(jax.random.key(1))


def _prompts(lengths, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (s,)).astype(np.int32) for s in lengths]


def _poison_slot_kv(engine, slot):
    """NaN one slot's live K storage, wherever the layout keeps it: the
    slot's batch index on the dense slab, the slot's physical pages when
    paged (index 1 of a paged pool is a PAGE, not a slot — and page 0 is the
    shared null page, which must stay finite)."""
    if engine.paged:
        pages = np.asarray(engine.cache.pages_of(slot), np.int32)
        engine.cache.k = engine.cache.k.at[:, pages].set(jnp.nan)
    else:
        engine.cache.k = engine.cache.k.at[:, slot].set(jnp.nan)


def _warm_program_count(engine, warmup=False):
    """Programs a fully-warmed engine holds: one decode step, plus one
    prefill program per bucket — and on the dense layout a separate insert
    program per bucket (paged prefill scatters into the pool directly).
    ``warmup=True`` counts what ``warmup()`` compiles, which for a paged
    engine adds the handoff pair (page extract + adopt-insert) that
    disaggregated steady state must never compile mid-traffic."""
    per_bucket = 1 if engine.paged else 2
    handoff_pair = 2 if warmup and engine.paged else 0
    return 1 + per_bucket * len(engine.buckets) + handoff_pair


# -- slot allocator -----------------------------------------------------------


def test_slot_allocator_admit_retire_reuse():
    alloc = SlotAllocator(3)
    slots = [alloc.admit() for _ in range(3)]
    assert sorted(slots) == [0, 1, 2]
    assert alloc.admit() is None  # full
    assert alloc.occupancy == 1.0
    alloc.retire(slots[1])
    assert alloc.free_count == 1
    assert alloc.admit() == slots[1]  # immediate reuse of the freed slot
    with pytest.raises(ValueError, match="not in use"):
        alloc.retire(99)


def test_prefill_bucket_set_is_logarithmic():
    buckets = prefill_buckets(255)
    assert buckets == (16, 32, 64, 128, 255)
    assert bucket_for(1, buckets) == 16
    assert bucket_for(16, buckets) == 16
    assert bucket_for(17, buckets) == 32
    assert bucket_for(255, buckets) == 255
    with pytest.raises(ValueError, match="exceeds"):
        bucket_for(256, buckets)
    # tiny caches collapse to one bucket
    assert prefill_buckets(8) == (8,)


def test_kv_cache_bytes_formula():
    from accelerate_tpu.models import get_config

    cfg = get_config("llama-tiny")  # 2 layers, 2 kv heads, 32 dim/head
    got = kv_cache_bytes(cfg, batch=4, max_seq_len=128, dtype_bytes=2)
    assert got == 2 * 2 * 2 * 32 * 128 * 4 * 2


# -- the acceptance invariants ------------------------------------------------


def test_generate_many_matches_sequential_generate(llama):
    """Mixed prompt lengths through the engine == per-request generate(),
    bit-exact at temperature 0 — the continuous batching is invisible."""
    model, params = llama
    prompts = _prompts([3, 7, 12, 16])
    engine = ServingEngine(model, params, num_slots=2, max_len=64, eos_token_id=5)
    outs = engine.generate_many(prompts, max_new_tokens=6)
    for prompt, out in zip(prompts, outs):
        expected = generate(model, params, prompt[None], max_new_tokens=6, eos_token_id=5)[0]
        np.testing.assert_array_equal(out, np.asarray(expected))


def test_generate_many_matches_generate_gpt2(gpt2):
    """Same invariant through a model-owned decode protocol (GPT2 methods)."""
    model, params = gpt2
    prompts = _prompts([4, 9, 14], seed=2)
    engine = ServingEngine(model, params, num_slots=3, max_len=48)
    outs = engine.generate_many(prompts, max_new_tokens=5)
    for prompt, out in zip(prompts, outs):
        expected = generate(model, params, prompt[None], max_new_tokens=5)[0]
        np.testing.assert_array_equal(out, np.asarray(expected))


def test_zero_steady_state_recompiles(llama):
    """After warmup (one prefill program per bucket — plus an insert program
    each on the dense layout — and one decode program), streaming requests
    with >= 4 distinct prompt lengths must compile NOTHING and miss the jit
    cache NEVER."""
    _, params = llama
    model = Llama("llama-tiny")  # fresh instance: clean jit cache, order-independent counts
    engine = ServingEngine(model, params, num_slots=4, max_len=64, buckets=(8, 16, 32))
    tracker = CompileTracker().start()
    engine.generate_many(_prompts([5, 13, 30], seed=3), max_new_tokens=3)  # warm every bucket
    warm = tracker.snapshot()
    assert warm["jit_cache_misses"] == _warm_program_count(engine)

    for prompt in _prompts([3, 7, 9, 14, 17, 25, 31, 6, 12, 28], seed=4):
        engine.submit(prompt, max_new_tokens=8)
    engine.run()
    steady = tracker.snapshot()
    tracker.stop()
    assert steady["compile_count"] == warm["compile_count"]
    assert steady["jit_cache_misses"] == warm["jit_cache_misses"]
    assert steady["jit_cache_hits"] > warm["jit_cache_hits"]


# -- scheduling behavior ------------------------------------------------------


def test_slot_contention_queues_and_reuses(llama):
    """More requests than slots: the queue drains through retirement, every
    request completes, and concurrency never exceeds the slot count."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    outs = engine.generate_many(_prompts([4, 6, 9], seed=5), max_new_tokens=4)
    assert len(outs) == 3
    assert engine.stats.requests_completed == 3
    assert engine.stats.max_active == 1
    # serially through one slot: one decode step per token
    assert engine.stats.steps == 3 * 4


def test_eos_retirement_frees_slot_next_step(llama):
    """A request hitting EOS retires immediately: the slot serves the queue
    on the very next step instead of idling to max_new_tokens."""
    model, params = llama
    prompt = _prompts([6], seed=6)[0]
    # find the greedy continuation and use its second token as "EOS"
    ref = np.asarray(generate(model, params, prompt[None], max_new_tokens=8))[0]
    eos = int(ref[prompt.size + 1])
    engine = ServingEngine(model, params, num_slots=1, max_len=64, eos_token_id=eos)
    engine.submit(prompt, max_new_tokens=8)
    engine.submit(_prompts([4], seed=7)[0], max_new_tokens=2)
    results = engine.run()
    first = results[0]
    assert first.finish_reason == "eos"
    assert len(first.generated) == 2  # stopped at the EOS hit, not at 8
    assert first.generated[-1] == eos
    assert results[1].finish_reason == "length"
    # 2 steps for the eos request + 2 for the queued one
    assert engine.stats.steps == 4


def test_admission_control_queue_full(llama):
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32, max_queue=2)
    engine.submit(_prompts([3])[0], max_new_tokens=2)
    engine.submit(_prompts([3])[0], max_new_tokens=2)
    with pytest.raises(QueueFull):
        engine.submit(_prompts([3])[0], max_new_tokens=2)
    assert engine.stats.requests_rejected == 1
    engine.run()


def test_queue_full_carries_depth_and_retry_after(llama):
    """Satellite: a shed request gets actionable guidance — the queue depth
    at rejection and a retry_after estimate from the measured service rate."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32, max_queue=2)
    for _ in range(2):
        engine.submit(_prompts([3])[0], max_new_tokens=2)
    with pytest.raises(QueueFull) as exc_info:
        engine.submit(_prompts([3])[0], max_new_tokens=2)
    e = exc_info.value
    assert e.queue_depth == 2
    assert e.retry_after_s is not None and e.retry_after_s > 0
    assert "retry in" in str(e)
    engine.run()
    # with service history the hint tracks the measured rate, still positive
    for _ in range(2):
        engine.submit(_prompts([3])[0], max_new_tokens=2)
    with pytest.raises(QueueFull) as exc_info:
        engine.submit(_prompts([3])[0], max_new_tokens=2)
    assert exc_info.value.retry_after_s > 0
    engine.run()


# -- degradation (resilience PR) ----------------------------------------------


def test_expired_queued_request_sheds_without_ever_taking_a_slot(llama):
    """A queued request past its deadline is retired at the top of the next
    step — it never consumes a prefill or a slot."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    live = engine.submit(_prompts([4], seed=20)[0], max_new_tokens=3)
    doomed = engine.submit(_prompts([4], seed=21)[0], max_new_tokens=3, deadline_s=0.0)
    results = engine.run()
    assert results[doomed].finish_reason == "expired"
    assert results[doomed].generated.size == 0
    assert results[live].finish_reason == "length"
    assert engine.stats.requests_expired == 1
    # the live request was the only one ever decoded
    assert engine.stats.steps == 3


def test_expired_active_request_frees_slot_by_next_step(llama):
    """An ACTIVE request whose deadline passes is retired at the top of the
    next step, and its slot serves the queue immediately."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    a = engine.submit(_prompts([4], seed=22)[0], max_new_tokens=8)
    b = engine.submit(_prompts([5], seed=23)[0], max_new_tokens=2)
    engine.step()  # A admitted + one decode
    engine.scheduler.slots[0].deadline_s = 0.0  # deterministic expiry, no sleeps
    results = {}
    while engine.busy:
        for r in engine.step():
            results[r.request_id] = r
    assert results[a].finish_reason == "expired"
    assert 1 <= results[a].generated.size < 8  # partial output survives
    assert results[b].finish_reason == "length"
    assert len(results[b].generated) == 2
    # A decoded once, B twice — the expired slot never burned another step
    assert engine.stats.steps == 3


def test_cancel_queued_and_active_requests(llama):
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    active = engine.submit(_prompts([4], seed=24)[0], max_new_tokens=8)
    queued = engine.submit(_prompts([4], seed=25)[0], max_new_tokens=8)
    engine.step()
    assert engine.cancel(queued)   # still waiting
    assert engine.cancel(active)   # mid-decode
    assert not engine.cancel(9999)  # unknown id
    results = {}
    while engine.busy:
        for r in engine.step():
            results[r.request_id] = r
    assert results[active].finish_reason == "cancelled"
    assert results[queued].finish_reason == "cancelled"
    assert engine.stats.requests_cancelled == 2
    # the engine is healthy afterwards: a fresh request completes normally
    out = engine.generate_many([_prompts([3], seed=26)[0]], max_new_tokens=2)
    assert len(out) == 1


def test_quarantine_requeue_and_probe_release(llama):
    """A slot producing non-finite logits is quarantined, its request requeues
    and completes correctly in a clean admission; the slot re-enters
    circulation only after the finite-logits probe passes."""
    import jax.numpy as jnp

    model, params = llama
    prompt = _prompts([5], seed=27)[0]
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    rid = engine.submit(prompt, max_new_tokens=4)
    engine.step()  # admit + first decode (healthy)
    # poison the slot's live K storage: next decode's logits go non-finite
    _poison_slot_kv(engine, 0)
    results = engine.run()
    assert engine.stats.slot_quarantines == 1
    assert engine.stats.requests_requeued == 1
    assert engine.stats.slot_quarantine_releases == 1
    assert engine.cache.quarantined == frozenset()
    # the requeued request restarted from its prompt and finished correctly:
    # greedy output matches the sequential reference exactly
    expected = np.asarray(
        generate(model, params, prompt[None], max_new_tokens=4)
    )[0][prompt.size:]
    np.testing.assert_array_equal(results[rid].generated, expected)
    assert results[rid].finish_reason == "length"


def test_quarantined_slot_never_serves_until_probe_passes(llama):
    """While a slot is quarantined it is invisible to admission: with every
    slot quarantined, a waiting request stays queued until the probe passes."""
    import jax.numpy as jnp

    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    engine.submit(_prompts([4], seed=28)[0], max_new_tokens=2)
    engine.step()
    _poison_slot_kv(engine, 0)
    engine.step()  # quarantine fires; request back at queue head
    assert engine.cache.quarantined == frozenset({0})
    assert engine.scheduler.waiting == 1
    assert engine.scheduler.active_slots == []
    engine.step()  # probe-only step: slot released at the end
    assert engine.cache.quarantined == frozenset()
    assert engine.scheduler.waiting == 1  # admission happens NEXT step
    results = engine.run()
    assert all(r.finish_reason == "length" for r in results.values())


def test_request_fails_after_max_requeues_instead_of_livelocking(llama):
    """A request that keeps landing in quarantined slots (e.g. its own input
    drives the model non-finite) fails after max_request_requeues instead of
    requeue-cycling forever — run() terminates and everyone else is served."""
    import jax.numpy as jnp

    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    rid = engine.submit(_prompts([4], seed=30)[0], max_new_tokens=4)
    engine.step()
    # simulate a request already bounced through bad slots up to the cap
    engine.scheduler.slots[0].requeues = engine.max_request_requeues
    _poison_slot_kv(engine, 0)
    results = engine.run()
    assert results[rid].finish_reason == "failed"
    assert engine.stats.requests_failed == 1
    assert engine.stats.requests_requeued == 0  # failed, not requeued again
    # engine stays healthy: the slot probed back and serves new requests
    out = engine.generate_many([_prompts([3], seed=31)[0]], max_new_tokens=2)
    assert len(out) == 1


def test_watchdog_reports_oversized_step(llama):
    """A decode step exceeding step_timeout_s is reported (stats counter) even
    when it completes — the synchronous arm of the watchdog."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32, step_timeout_s=1e-9)
    engine.generate_many([_prompts([3], seed=29)[0]], max_new_tokens=2)
    assert engine.stats.watchdog_trips >= 1
    assert "watchdog_trips" in engine.metrics()


def test_step_watchdog_thread_fires_on_hang():
    """The wall-clock arm: a step that never returns is reported from the
    side thread while the 'host' (this test) is still blocked."""
    from accelerate_tpu.serving.engine import StepWatchdog

    trips = []
    watchdog = StepWatchdog(0.05, trips.append, poll_s=0.01)
    try:
        watchdog.arm()
        deadline = time.monotonic() + 2.0
        while not trips and time.monotonic() < deadline:
            time.sleep(0.01)  # the "hung" step
        assert trips, "watchdog never fired on a hung step"
        assert len(trips) == 1  # one trip per armed step
        watchdog.disarm()
    finally:
        watchdog.close()


def test_submit_validates_capacity(llama):
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=16)
    with pytest.raises(ValueError, match="slot capacity"):
        engine.submit(np.arange(10, dtype=np.int32), max_new_tokens=10)
    with pytest.raises(ValueError, match="at least one token"):
        engine.submit(np.zeros((0,), np.int32))
    # single-token prompts skip prefill entirely
    out = engine.generate_many([np.asarray([7], np.int32)], max_new_tokens=3)[0]
    expected = generate(model, params, np.asarray([[7]], np.int32), max_new_tokens=3)[0]
    np.testing.assert_array_equal(out, np.asarray(expected))


# -- loaders ------------------------------------------------------------------


def test_engine_from_streamed_int8(gpt2):
    """int8 serving load path: dispatch_model's quantized host image →
    on-device dequantized resident params → the engine, matching generate()
    on the same dequantized weights exactly."""
    from accelerate_tpu.big_modeling import dispatch_model, make_layered_device_map
    from accelerate_tpu.utils.quantization import QuantizationConfig

    model, params = gpt2
    streamed = dispatch_model(
        model, params, make_layered_device_map(model, "cpu"),
        dtype=jnp.float32, quantization=QuantizationConfig(load_in_8bit=True),
    )
    qparams = params_from_streamed(streamed)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(qparams)):
        assert a.shape == b.shape and b.dtype == jnp.float32
    engine = ServingEngine.from_streamed(streamed, num_slots=2, max_len=48)
    prompts = _prompts([5, 9], seed=8)
    outs = engine.generate_many(prompts, max_new_tokens=4)
    for prompt, out in zip(prompts, outs):
        expected = generate(model, qparams, prompt[None], max_new_tokens=4)[0]
        np.testing.assert_array_equal(out, np.asarray(expected))


# -- telemetry ----------------------------------------------------------------


def test_serving_stats_and_telemetry_record(llama, tmp_path):
    from accelerate_tpu.telemetry import Telemetry, TelemetryConfig

    model, params = llama
    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    engine = ServingEngine(model, params, num_slots=2, max_len=32, telemetry=hub)
    engine.generate_many(_prompts([3, 5, 8], seed=9), max_new_tokens=4)
    metrics = engine.metrics()
    for key in (
        "throughput_tokens_per_sec", "slot_occupancy", "ttft_p50_ms", "ttft_p99_ms",
        "per_token_p50_ms", "per_token_p99_ms", "tokens_generated", "compile_count",
        "jit_cache_hits",
    ):
        assert key in metrics, key
    assert metrics["tokens_generated"] == 3 * 4
    assert metrics["requests_completed"] == 3
    assert 0 < metrics["slot_occupancy"] <= 1
    record = engine.flush_telemetry()
    assert record["kind"] == "serving"
    hub.finish(flush=False)
    lines = [json.loads(l) for l in open(tmp_path / "telemetry.jsonl")]
    serving = [r for r in lines if r["kind"] == "serving"]
    assert serving and serving[0]["serving"]["requests_completed"] == 3


def test_run_offered_load_paced(llama):
    """The load generator paces arrivals and reports the sweep-point shape
    bench.py and serve-bench consume."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=2, max_len=32)
    point = run_offered_load(engine, _prompts([3, 4, 5, 6], seed=10), 3, offered_rps=200.0)
    assert point["requests_completed"] == 4
    assert point["offered_rps"] == 200.0
    assert point["tokens_generated"] == 4 * 3


def test_run_offered_load_backpressure_counts_in_ttft(llama):
    """A bounded queue under saturation sheds with a retry_after hint, and
    the loadgen honors it with jittered backoff instead of immediately
    re-offering: everything still completes, sheds and retries are counted
    separately and balance exactly (each shed schedules one retry), and the
    deferred requests' TTFT includes the backlog wait (backdated submit), so
    the tail TTFT strictly exceeds the unqueued one."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32, max_queue=1)
    point = run_offered_load(engine, _prompts([4, 5, 6, 7], seed=14), 4)
    assert point["requests_completed"] == 4
    assert point["offered_requests"] == 4
    # exact offered-load accounting: the engine's shed count is the
    # loadgen's, and every shed was re-offered exactly once
    assert point["requests_rejected"] == point["loadgen_sheds"]
    assert point["loadgen_sheds"] == point["loadgen_retries"]
    assert point["loadgen_sheds"] > 0  # saturation really did shed
    # last-admitted request waited for ~3 predecessors × 4 decode steps
    assert point["ttft_p99_ms"] > point["ttft_p50_ms"]


def test_engine_warmup_compiles_every_bucket(llama):
    """warmup() deterministically compiles one prefill program per bucket
    (plus a dense layout's insert pair) + the decode step; any traffic mix
    afterwards compiles nothing."""
    _, params = llama
    model = Llama("llama-tiny")  # fresh jit cache
    engine = ServingEngine(model, params, num_slots=2, max_len=64, buckets=(8, 16, 32))
    tracker = CompileTracker().start()
    engine.warmup()
    warm = tracker.snapshot()
    assert warm["jit_cache_misses"] == _warm_program_count(engine, warmup=True)
    engine.generate_many(_prompts([3, 9, 20, 31], seed=13), max_new_tokens=4)
    steady = tracker.snapshot()
    tracker.stop()
    assert steady["compile_count"] == warm["compile_count"]
    assert steady["jit_cache_misses"] == warm["jit_cache_misses"]


# -- generation satellites (device-side EOS mask) -----------------------------


def test_generate_eos_with_return_device(llama):
    """eos_token_id now composes with return_device: the done-mask runs on
    device, so the returned device array is already EOS-filled."""
    model, params = llama
    ids = _prompts([5], seed=11)[0][None]
    host = generate(model, params, ids, max_new_tokens=6, eos_token_id=5)
    dev = generate(model, params, ids, max_new_tokens=6, eos_token_id=5, return_device=True)
    assert not isinstance(dev, np.ndarray)
    np.testing.assert_array_equal(np.asarray(dev), host)


def test_generate_done_mask_matches_host_truncation_semantics(llama):
    """Pick an EOS id that the greedy run actually emits mid-stream: output
    before the first EOS is unchanged, everything after is EOS — exactly the
    old host-side truncation, now produced on device."""
    model, params = llama
    ids = _prompts([4, 6], seed=12)
    batch = np.stack([np.pad(p, (0, 6 - p.size)) for p in ids])[:, :4].astype(np.int32)
    free = np.asarray(generate(model, params, batch, max_new_tokens=8))
    eos = int(free[0, 4 + 2])  # third generated token of row 0
    with_eos = np.asarray(generate(model, params, batch, max_new_tokens=8, eos_token_id=eos))
    expected = free.copy()
    for row in range(expected.shape[0]):
        hits = np.where(expected[row, 4:] == eos)[0]
        if hits.size:
            expected[row, 4 + hits[0] + 1 :] = eos
    np.testing.assert_array_equal(with_eos, expected)
