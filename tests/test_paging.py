"""Paged KV-cache subsystem (serving/paging.py): block allocator, COW prefix
sharing, chunked prefill — and the engine-level invariants that make paging
invisible: temp-0 bit-equality against the dense slot cache and against
sequential generate, zero steady-state recompiles (routed included), page
exhaustion degrading to QueueFull/preemption instead of deadlock.

All tier-1-fast on the CPU mesh — like test_serving.py, the fixed-shape
compile invariants proven here are the TPU ones.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import Llama
from accelerate_tpu.models.generation import generate
from accelerate_tpu.serving import (
    PageAllocator,
    PagedKVCache,
    PrefixCache,
    QueueFull,
    ServingEngine,
    ServingRouter,
    make_mixed_prompts,
    pages_for,
)
from accelerate_tpu.serving.paging import paged_buckets
from accelerate_tpu.telemetry import CompileTracker


@pytest.fixture(scope="module")
def llama():
    model = Llama("llama-tiny")
    return model, model.init(jax.random.key(0))


def _prompts(lengths, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (s,)).astype(np.int32) for s in lengths]


# -- pure host bookkeeping ----------------------------------------------------


def test_pages_for_and_paged_buckets():
    assert pages_for(1, 16) == 1
    assert pages_for(16, 16) == 1
    assert pages_for(17, 16) == 2
    # buckets round UP to page multiples and cap at the backed capacity
    assert paged_buckets((8, 16, 31), 16, 64) == (16, 32)
    assert paged_buckets((100,), 16, 64) == (64,)
    with pytest.raises(ValueError, match="no usable"):
        paged_buckets((0,), 16, 64)


def test_page_allocator_walk():
    alloc = PageAllocator(4)  # null page + 3 real
    assert alloc.free_count == 3 and alloc.used_count == 0
    a = alloc.alloc()
    assert a == 1  # page 0 is never handed out
    b, c = alloc.alloc(), alloc.alloc()
    assert sorted([a, b, c]) == [1, 2, 3]
    assert alloc.alloc() is None  # exhausted
    assert alloc.occupancy == 1.0
    # refcount / COW-fork: a second holder shares, frees only at the last drop
    alloc.fork([b])
    assert alloc.is_shared(b)
    assert alloc.decref(b) is False  # one holder remains
    assert alloc.decref(b) is True  # now actually free
    assert alloc.free_count == 1
    assert alloc.alloc() == b  # LIFO reuse of the freed page
    # misuse is loud
    alloc.decref(c)
    with pytest.raises(ValueError, match="already free"):
        alloc.decref(c)
    with pytest.raises(ValueError, match="cannot share"):
        alloc.incref(c)
    # the null page is pinned: refcount ops are no-ops, never freed
    assert alloc.decref(0) is False
    alloc.incref(0)
    assert not alloc.is_shared(0)
    # all-or-nothing bulk allocation
    assert alloc.alloc_many(5) is None
    assert PageAllocator(3).alloc_many(2) == [1, 2]
    with pytest.raises(ValueError, match=">= 2"):
        PageAllocator(1)


def test_prefix_cache_register_lookup_evict():
    alloc = PageAllocator(8)
    cache = PrefixCache(alloc, page_size=4, max_entries=2)
    tokens = np.arange(8, dtype=np.int32)
    p0, p1 = alloc.alloc(), alloc.alloc()
    assert cache.register_chain(tokens, [p0, p1]) == 2
    assert alloc.refcounts[p0] == 2  # registry holds its own reference
    # full-chain hit, partial-prefix hit, divergent-suffix hit
    hit, pages = cache.lookup(tokens)
    assert (hit, pages) == (8, [p0, p1])
    hit, pages = cache.lookup(tokens[:6])
    assert (hit, pages) == (4, [p0])
    divergent = np.concatenate([tokens[:4], tokens[:4] + 99])
    hit, pages = cache.lookup(divergent)
    assert (hit, pages) == (4, [p0])
    # a digest collision degrades to a shorter hit, never to wrong K/V:
    # tamper the stored block so the digest matches but the tokens do not
    digest = cache._chain(b"", tokens[:4])
    page, _ = cache._entries[digest]
    cache._entries[digest] = (page, tokens[:4] + 1)
    assert cache.lookup(tokens) == (0, [])
    cache._entries[digest] = (page, tokens[:4].copy())
    # registering a third chain evicts LRU (max_entries=2) and drops its ref
    p2 = alloc.alloc()
    other = np.arange(100, 104, dtype=np.int32)
    cache.register_chain(other, [p2])
    assert len(cache) == 2 and cache.evictions == 1
    # pressure eviction walks LRU until enough pages free (or registry empty)
    before = alloc.free_count
    cache.evict_for_pressure(before + 2)
    assert alloc.free_count > before or len(cache) == 0


def test_paged_cache_cow_and_pressure_walk(llama):
    from accelerate_tpu.models.generation import resolve_decode_protocol

    model, _ = llama
    init_cache, _ = resolve_decode_protocol(model)
    cache = PagedKVCache(init_cache, num_slots=2, max_len=16, page_size=4, num_pages=6)
    # admit with a shared (forked) page + one private page
    donor = cache.pages.alloc()
    slot = cache.admit([donor], new_pages=1)
    assert slot is not None
    assert cache.pages.refcounts[donor] == 2  # donor's ref + this slot's fork
    assert cache.held[slot] == 2
    # a write landing mid-way into the SHARED page triggers COW: replacement
    # allocated, table swapped, caller told to copy donor -> dst
    cache.lengths[slot] = 2
    status, src, dst = cache.prepare_write(slot)
    assert status == "cow" and src == donor and dst not in (0, donor)
    assert cache.tables[slot, 0] == dst
    assert cache.pages.refcounts[donor] == 1  # the fork moved off it
    # private page: plain ok
    assert cache.prepare_write(slot) == ("ok", 0, 0)
    # crossing past the held pages grows by one
    cache.lengths[slot] = 8
    assert cache.prepare_write(slot)[0] == "grow"
    assert cache.held[slot] == 3
    # pool dry (5 usable: donor + 3 held + 1) -> grow fails, pressure
    assert cache.grow(slot, 1)
    cache.lengths[slot] = 16 - 1
    assert cache.pages.free_count == 0
    cache.lengths[slot] = 12  # next write would need a 5th page
    cache.held[slot] = 3  # pretend the 4th wasn't there: force a grow
    assert cache.prepare_write(slot) == ("pressure", 0, 0)
    # retire releases the slot's references; the donor page survives (ours)
    cache.retire(0) if slot == 0 else cache.retire(slot)
    assert cache.pages.refcounts[donor] == 1


def test_fork_partial_rollback_refcount_cycle(llama):
    """The speculative tree-branch page protocol, engine-independent: fork a
    slot's committed pages for a branch, COW off the shared boundary page,
    grow for the candidate window, roll back over a partially-accepted
    (page-unaligned) tail, release the branch — every refcount accounted,
    the pool drains to zero."""
    from accelerate_tpu.models.generation import resolve_decode_protocol

    model, _ = llama
    init_cache, _ = resolve_decode_protocol(model)
    cache = PagedKVCache(init_cache, num_slots=2, max_len=32, page_size=4, num_pages=10)
    slot = cache.admit([], new_pages=3)
    cache.lengths[slot] = 10  # unaligned: page 2 holds positions 8-9 only
    committed = cache.pages_of(slot)
    assert len(committed) == 3

    # a branch forks the committed prefix: refcount, no copy
    cache.pages.fork(committed)
    assert all(cache.pages.is_shared(p) for p in committed)

    # the slot's next write lands in the now-SHARED boundary page -> COW:
    # the slot moves to a private replacement, the branch keeps the original
    status, src, dst = cache.prepare_write(slot)
    assert status == "cow" and src == committed[2]
    assert int(cache.tables[slot, 2]) == dst
    assert cache.pages.refcounts[committed[2]] == 1  # the branch's ref
    assert not cache.pages.is_shared(dst)

    # speculative grow for the candidate window, then acceptance lands short
    # of the window (9 < 10 committed? no — 9 tokens keep 3 pages): the
    # surplus window page is PRIVATE and must actually free
    assert cache.grow(slot, 1)
    window_page = int(cache.tables[slot, 3])
    cache.lengths[slot] = 9
    assert cache.trim_to_length(slot) == [window_page]
    assert cache.held[slot] == 3

    # rollback BELOW shared coverage un-shares, never frees under the branch
    cache.lengths[slot] = 4  # keep only page 0
    freed = cache.trim_to_length(slot)
    # committed[1] was shared (branch holds it) -> not freed; the COW
    # replacement dst was private -> freed
    assert freed == [dst]
    assert cache.pages.refcounts[committed[1]] == 1
    assert cache.held[slot] == 1

    # branch release: last holder frees, shared holder just un-shares
    assert cache.pages.decref(committed[0]) is False  # slot still holds it
    assert cache.pages.decref(committed[1]) is True
    assert cache.pages.decref(committed[2]) is True

    # retire the slot: the pool is fully drained — no leaked references
    cache.retire(slot)
    assert cache.pages.used_count == 0
    assert cache.pages.free_count == 9


# -- engine: equality, exhaustion, sharing, chunking --------------------------


def test_paged_matches_dense_and_sequential_bit_exact(llama):
    """The acceptance bar: paged vs dense slot-cache generation bit-equal at
    temperature 0 on a mixed-length workload (page-aligned and not), both
    equal to per-request sequential generate."""
    model, params = llama
    prompts = _prompts([3, 8, 13, 17, 24, 31], seed=40)
    paged = ServingEngine(
        model, params, num_slots=3, max_len=64, paged=True, page_size=8
    )
    dense = ServingEngine(model, params, num_slots=3, max_len=64, paged=False)
    out_paged = paged.generate_many(prompts, max_new_tokens=6)
    out_dense = dense.generate_many(prompts, max_new_tokens=6)
    for prompt, a, b in zip(prompts, out_paged, out_dense):
        np.testing.assert_array_equal(a, b)
        expected = generate(model, params, prompt[None], max_new_tokens=6)[0]
        np.testing.assert_array_equal(a, np.asarray(expected))
    assert paged.stats.peak_pages_in_use > 0


def test_page_exhaustion_sheds_queuefull_with_retry_hint(llama):
    """Admission is gated on PAGES: with the pool pinned by an active
    request, a queued request waits, and past max_queue the submit sheds
    with the page-pressure-aware retry_after_s hint."""
    model, params = llama
    engine = ServingEngine(
        model, params, num_slots=2, max_len=32, page_size=8, num_pages=3,
        max_queue=1,
    )
    # A: prefill span 16 = both usable pages
    a = engine.submit(_prompts([9], seed=41)[0], max_new_tokens=8)
    engine.step()  # A admitted and decoding
    b = engine.submit(_prompts([9], seed=42)[0], max_new_tokens=8)
    engine.step()
    assert engine.scheduler.waiting == 1  # B has a free SLOT but no pages
    with pytest.raises(QueueFull) as excinfo:
        engine.submit(_prompts([9], seed=43)[0], max_new_tokens=8)
    assert excinfo.value.retry_after_s > 0
    assert engine.stats.requests_rejected == 1
    # the pool is not deadlocked: A retires, B admits and completes
    results = engine.run()
    assert results[a].finish_reason == "length"
    assert results[b].finish_reason == "length"


def test_infeasible_bucketed_span_rejected_not_deadlocked(llama):
    """A request whose BUCKETED first prefill span needs more pages than the
    pool holds must shed at submit — queued, admission would never succeed
    and the queue would deadlock (the raw token count can fit while the
    padded span does not)."""
    model, params = llama
    engine = ServingEngine(
        model, params, num_slots=1, max_len=16, page_size=4, num_pages=4
    )
    assert engine.buckets == (16,)  # one bucket: any prefill pads to 4 pages
    with pytest.raises(ValueError, match="needs 4 pages"):
        engine.submit(_prompts([6], seed=44)[0], max_new_tokens=2)  # 8 tokens total


def test_admit_under_pressure_never_reissues_hit_pages(llama):
    """Admission forks the prefix-hit pages BEFORE allocating the private
    suffix: ``_alloc`` may evict prefix-cache entries under pressure, and a
    hit page held only by the registry would otherwise be freed mid-admission
    and handed back out as a "fresh" page — the same physical page twice in
    one table row, silently corrupting attention."""
    from accelerate_tpu.models.generation import resolve_decode_protocol

    model, _ = llama
    init_cache, _ = resolve_decode_protocol(model)
    cache = PagedKVCache(init_cache, num_slots=2, max_len=24, page_size=4, num_pages=6)
    tokens = np.arange(8, dtype=np.int32)
    held = cache.pages.alloc_many(2)
    cache.prefix.register_chain(tokens, held)
    for page in held:
        cache.pages.decref(page)  # the registry is now the pages' only holder
    hit, shared = cache.prefix.lookup(tokens)
    assert (hit, shared) == (8, held)
    # 3 pages free, 4 needed: eviction fires inside _alloc but must not free
    # the forked hit pages — the admission fails cleanly instead
    assert cache.admit(shared, new_pages=4) is None
    # and rolls back completely: lane free, every usable page back in the pool
    assert cache.lanes.occupancy == 0.0
    assert cache.pages.free_count == cache.num_pages - 1
    # a feasible shared admission yields a row of DISTINCT pages
    tokens2 = np.arange(50, 58, dtype=np.int32)
    held2 = cache.pages.alloc_many(2)
    cache.prefix.register_chain(tokens2, held2)
    for page in held2:
        cache.pages.decref(page)
    _, shared2 = cache.prefix.lookup(tokens2)
    slot = cache.admit(shared2, new_pages=3)
    assert slot is not None
    row = cache.pages_of(slot)
    assert len(set(row)) == len(row) == 5


def test_chunked_final_span_padding_counts_in_feasibility(llama):
    """The submit-time page bound must cover every chunk boundary's PADDED
    span: the final chunk's tail buckets up, so mid-flight the table can
    need more pages than either the raw token count or the first span —
    such a request sheds at submit instead of failing on an idle engine."""
    model, params = llama
    engine = ServingEngine(
        model, params, num_slots=1, max_len=48, page_size=4, num_pages=12,
        prefill_chunk=32,
    )
    # 41 prefill tokens: chunk 32 (8 pages) + 9-token tail bucketed to 16
    # -> peak (32+16)//4 = 12 pages > 11 usable, though 42 raw tokens fit
    with pytest.raises(ValueError, match="needs 12 pages"):
        engine.submit(_prompts([42], seed=57)[0], max_new_tokens=1)
    # one more page and the same request admits and completes
    roomy = ServingEngine(
        model, params, num_slots=1, max_len=48, page_size=4, num_pages=13,
        prefill_chunk=32,
    )
    rid = roomy.submit(_prompts([42], seed=57)[0], max_new_tokens=1)
    assert roomy.run()[rid].finish_reason == "length"


def test_span_never_overflows_page_table_chunked_or_hit(llama):
    """Every prefill span must land inside the fixed-width page table even
    when ``view_len`` is not a chunk multiple: the chunk cadence whose
    bucket-padded tail would overflow degrades to one monolithic bucket
    span, and a prefix hit that would leave an unlandable tail is capped
    (part of the prefix re-prefills) instead of overflowing the table row."""
    model, params = llama
    # (a) chunked: view_len 20, chunks at 0/8/16 would pad the 3-token tail
    # to bucket 8 -> position 24 > 20. Must fall back to the 20-bucket span.
    engine = ServingEngine(
        model, params, num_slots=2, max_len=20, page_size=4, prefill_chunk=8
    )
    prompt = _prompts([20], seed=59)[0]
    rid = engine.submit(prompt, max_new_tokens=1)
    results = engine.run()
    assert results[rid].finish_reason == "length"
    expected = np.asarray(generate(model, params, prompt[None], max_new_tokens=1))
    np.testing.assert_array_equal(results[rid].generated, expected[0][prompt.size:])
    # (b) prefix hit: a registered 16-token prefix + a 19-token prefill
    # leaves a 3-token suffix whose bucket pads past view_len; the hit is
    # capped so the schedule fits, rather than overflowing admit()
    engine2 = ServingEngine(model, params, num_slots=2, max_len=20, page_size=4)
    system = _prompts([16], seed=60)[0]
    engine2.generate_many([np.concatenate([system, system[:1]])], max_new_tokens=1)
    full = np.concatenate([system, _prompts([4], seed=61)[0]])  # prefill 19
    rid2 = engine2.submit(full, max_new_tokens=1)
    results2 = engine2.run()
    assert results2[rid2].finish_reason == "length"
    expected2 = np.asarray(generate(model, params, full[None], max_new_tokens=1))
    np.testing.assert_array_equal(results2[rid2].generated, expected2[0][full.size:])


def test_warmup_covers_spans_traffic_reaches_via_prefix_hits(llama):
    """A prefix hit can route ``_next_span`` to a monolithic span no
    synthetic warmup request's own schedule selects (hit 16 -> remaining 79
    -> the chunk cadence overflows view_len 96 -> fallback bucket 80).
    Warmup compiles every span program directly, so even that schedule
    compiles nothing in steady state — and a single-span fallback prefill
    is NOT counted as chunked-prefill activity."""
    _, params = llama
    model = Llama("llama-tiny")  # fresh jit cache
    engine = ServingEngine(
        model, params, num_slots=2, max_len=96, page_size=16,
        prefill_chunk=32, buckets=(32, 48, 64, 80, 96),
    )
    tracker = CompileTracker().start()
    engine.warmup()
    warm = tracker.snapshot()
    system = _prompts([16], seed=62)[0]
    register = np.concatenate([system, _prompts([1], seed=63)[0]])
    engine.generate_many([register], max_new_tokens=1)  # files the 16-token prefix
    long = np.concatenate([system, _prompts([80], seed=64)[0]])  # prefill 95
    out = engine.generate_many([long], max_new_tokens=1)[0]
    steady = tracker.snapshot()
    tracker.stop()
    assert engine.stats.prefix_hits == 1  # the hit actually routed the span
    assert steady["compile_count"] == warm["compile_count"]
    assert steady["jit_cache_misses"] == warm["jit_cache_misses"]
    # neither the 16-token single-bucket prefill nor the 80-span monolithic
    # fallback is chunked activity
    assert engine.stats.prefill_chunks == 0
    expected = np.asarray(generate(model, params, long[None], max_new_tokens=1))
    np.testing.assert_array_equal(out, expected[0])


def test_warmup_does_not_pin_prefix_cache(llama):
    """Warmup's synthetic bucket prompts stay out of the prefix cache: every
    page returns to the pool, no registry entries survive, and the hit-rate
    denominators real traffic reports are untouched."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=2, max_len=64, page_size=8)
    engine.warmup()
    assert len(engine.cache.prefix) == 0
    assert engine.cache.pages.free_count == engine.cache.num_pages - 1
    assert engine.stats.prefix_hits == 0 and engine.stats.prefix_misses == 0
    # real traffic still registers and hits, with exact accounting
    system = np.arange(16, dtype=np.int32) + 3
    prompts = [np.concatenate([system, t]) for t in _prompts([5, 7], seed=58)]
    engine.generate_many([prompts[0]], max_new_tokens=3)
    engine.generate_many([prompts[1]], max_new_tokens=3)
    assert engine.stats.prefix_hits == 1


def test_prefix_sharing_one_prefill_bit_equal_outputs(llama):
    """Two requests behind one system prompt: the second's shared pages are
    never re-prefilled (prefill token accounting proves it), refcounts track
    the fork, and outputs stay bit-equal to an engine with sharing off."""
    model, params = llama
    rng = np.random.default_rng(45)
    system = rng.integers(0, 1024, (16,)).astype(np.int32)
    tails = _prompts([5, 7], seed=46)
    prompts = [np.concatenate([system, t]) for t in tails]

    shared = ServingEngine(
        model, params, num_slots=2, max_len=64, page_size=8, prefix_sharing=True
    )
    # sequential: the first request registers the prefix, the second hits it
    out0 = shared.generate_many([prompts[0]], max_new_tokens=5)[0]
    out1 = shared.generate_many([prompts[1]], max_new_tokens=5)[0]
    assert shared.stats.prefix_hits == 1
    assert shared.stats.prefix_tokens_reused == 16
    # exactly one prefill of the shared pages: run 1 prefilled its full 32
    # bucket; run 2 only the 16-bucket covering its 6-token suffix — the 16
    # shared tokens were never prefilled again
    assert shared.stats.prefill_tokens == 32 + 16
    unshared = ServingEngine(
        model, params, num_slots=2, max_len=64, page_size=8, prefix_sharing=False
    )
    ref0 = unshared.generate_many([prompts[0]], max_new_tokens=5)[0]
    ref1 = unshared.generate_many([prompts[1]], max_new_tokens=5)[0]
    assert unshared.stats.prefix_hits == 0
    assert unshared.stats.prefill_tokens == 32 + 32
    np.testing.assert_array_equal(out0, ref0)
    np.testing.assert_array_equal(out1, ref1)


def test_prefix_sharing_concurrent_requests_fork_refcounts(llama):
    """A registered system prompt serves CONCURRENT sharers: both fork the
    same physical pages (refcount > 2 while both fly), neither re-prefills
    them, and outputs match sequential generate."""
    model, params = llama
    rng = np.random.default_rng(47)
    system = rng.integers(0, 1024, (16,)).astype(np.int32)
    prompts = [np.concatenate([system, t]) for t in _prompts([5, 9], seed=48)]
    engine = ServingEngine(model, params, num_slots=2, max_len=64, page_size=8)
    engine.generate_many([prompts[0]], max_new_tokens=2)  # registers the prefix
    ids = [engine.submit(p, max_new_tokens=5) for p in prompts]
    engine.step()  # both admitted in one step: both hit the registry
    assert engine.stats.prefix_hits == 2  # the warm run registered, these two hit
    shared_pages = [
        p for p in engine.cache.pages_of(0) if engine.cache.pages.refcounts[p] >= 3
    ]
    assert len(shared_pages) == 2  # both 8-token pages of the system prompt
    results = engine.run()
    for p, rid in zip(prompts, ids):
        expected = np.asarray(
            generate(model, params, p[None], max_new_tokens=5)
        )[0][p.size:]
        np.testing.assert_array_equal(results[rid].generated, expected)


def test_cow_write_copies_exactly_one_page(llama):
    """A decode write landing in a shared page copies THAT page only, on
    device: the original page's bytes are untouched, the copy diverges only
    at the written position, and the token stream is unchanged."""
    model, params = llama
    prompt = _prompts([5], seed=49)[0]
    engine = ServingEngine(model, params, num_slots=2, max_len=32, page_size=8)
    rid = engine.submit(prompt, max_new_tokens=4)
    engine.step()  # admit + prefill + first decode (length now 5)
    slot = 0
    page = int(engine.cache.tables[slot, 0])
    engine.cache.pages.incref(page)  # simulate another holder of the page
    before = np.asarray(engine.cache.k[:, page]).copy()
    engine.step()  # write pos 5 lands in the shared page -> COW
    assert engine.stats.cow_page_copies == 1
    dst = int(engine.cache.tables[slot, 0])
    assert dst != page
    after_src = np.asarray(engine.cache.k[:, page])
    np.testing.assert_array_equal(after_src, before)  # original untouched
    after_dst = np.asarray(engine.cache.k[:, dst])
    np.testing.assert_array_equal(after_dst[:, :5], before[:, :5])
    assert not np.array_equal(after_dst[:, 5], before[:, 5])  # the new write
    results = engine.run()
    expected = np.asarray(generate(model, params, prompt[None], max_new_tokens=4))
    np.testing.assert_array_equal(
        results[rid].generated, expected[0][prompt.size:]
    )


def test_chunked_prefill_preserves_admitted_decode_cadence(llama):
    """The TTFT-spike regression: with prefill_chunk set, a long prompt's
    prefill spreads one chunk per step, and an already-admitted short
    request keeps producing exactly one token per step throughout — its
    decode cadence never stalls behind the long prefill."""
    model, params = llama
    engine = ServingEngine(
        model, params, num_slots=2, max_len=48, page_size=8, prefill_chunk=8
    )
    short = engine.submit(_prompts([4], seed=50)[0], max_new_tokens=10)
    engine.step()  # short admitted, prefilled, first token out
    short_req = next(r for r in engine.scheduler.slots if r is not None and r.id == short)
    assert len(short_req.generated) == 1
    long_prompt = _prompts([33], seed=51)[0]  # prefill 32 = 4 chunks of 8
    lid = engine.submit(long_prompt, max_new_tokens=4)
    for step in range(4):  # the long prefill's 4 chunk steps
        engine.step()
        assert len(short_req.generated) == 2 + step  # cadence: +1 per step
    long_req = next(r for r in engine.scheduler.slots if r is not None and r.id == lid)
    assert long_req.prefilled == 32
    # the 4th chunk step made the long slot decode-visible that same step
    assert len(long_req.generated) == 1
    assert engine.stats.prefill_chunks >= 4
    results = engine.run()
    # split points change nothing: chunked output bit-equal sequential
    expected = np.asarray(generate(model, params, long_prompt[None], max_new_tokens=4))
    np.testing.assert_array_equal(
        results[lid].generated, expected[0][long_prompt.size:]
    )


def test_preemption_under_page_pressure_completes_all(llama):
    """When growth hits a dry pool, the youngest request preempts back to
    the queue head (recompute-style) instead of deadlocking; everyone still
    completes with sequential-bit-equal output."""
    model, params = llama
    prompts = _prompts([5, 5], seed=52)
    engine = ServingEngine(
        model, params, num_slots=2, max_len=16, page_size=4, num_pages=6,
        prefill_chunk=4,
    )
    ids = [engine.submit(p, max_new_tokens=11) for p in prompts]
    results = engine.run()
    assert engine.stats.requests_preempted >= 1
    assert engine.stats.page_pressure_events >= 1
    for p, rid in zip(prompts, ids):
        assert results[rid].finish_reason == "length"
        expected = np.asarray(generate(model, params, p[None], max_new_tokens=11))
        np.testing.assert_array_equal(results[rid].generated, expected[0][p.size:])


def test_null_page_stays_finite_with_idle_lanes(llama):
    """Idle decode lanes write to the null page every step — sanitized to
    zeros, so the page every unused table entry points at stays finite."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=4, max_len=32, page_size=8)
    engine.generate_many(_prompts([5], seed=53), max_new_tokens=6)  # 3 lanes idle
    assert bool(np.isfinite(np.asarray(engine.cache.k[:, 0])).all())
    assert bool(np.isfinite(np.asarray(engine.cache.v[:, 0])).all())


def test_quarantine_scrubs_freed_pages_on_device(llama):
    """A poisoned lane's fully-freed pages are zeroed on device before the
    pool recycles them — 0 × NaN is still NaN, so masking alone could not
    contain non-finite K/V handed to the pages' next holder."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32, page_size=8)
    engine.submit(_prompts([5], seed=54)[0], max_new_tokens=6)
    engine.step()
    pages = engine.cache.pages_of(0)
    engine.cache.k = engine.cache.k.at[:, np.asarray(pages)].set(jnp.nan)
    engine.step()  # non-finite verdict -> quarantine + device scrub
    assert engine.stats.slot_quarantines == 1
    for page in pages:
        np.testing.assert_array_equal(
            np.asarray(engine.cache.k[:, page], np.float32), 0.0
        )
    results = engine.run()  # probe releases the lane; the request completes
    assert engine.stats.slot_quarantine_releases == 1
    assert all(r.finish_reason == "length" for r in results.values())


def test_routed_paged_fleet_zero_steady_state_recompiles(llama):
    """The acceptance gate under the router: a 2-replica PAGED fleet (chunked
    prefill + prefix sharing on) streams mixed shared-prefix traffic with
    zero steady-state compiles per replica — page tables ride as program
    arguments, so no traffic mix can respecialize the decode program."""
    _, params = llama
    model = Llama("llama-tiny")  # fresh jit cache
    router = ServingRouter(
        engine_factory=lambda: ServingEngine(
            model, params, num_slots=2, max_len=64, page_size=8, prefill_chunk=16
        ),
        num_replicas=2,
    )
    tracker = CompileTracker().start()
    router.warmup()
    warm = tracker.snapshot()
    prompts = make_mixed_prompts(
        8, 1024, 4, 10, long_fraction=0.25, long_multiplier=4,
        shared_prefix=8, seed=55,
    )
    outs = router.generate_many(prompts, max_new_tokens=5)
    steady = tracker.snapshot()
    tracker.stop()
    assert steady["compile_count"] == warm["compile_count"]
    assert steady["jit_cache_misses"] == warm["jit_cache_misses"]
    metrics = router.metrics()
    assert metrics["prefix_hits"] > 0  # the shared prefix was actually reused
    for prompt, out in zip(prompts, outs):
        expected = generate(model, params, prompt[None], max_new_tokens=5)[0]
        np.testing.assert_array_equal(out, np.asarray(expected))
