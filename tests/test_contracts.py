"""Program contracts: the differential regression gate (docs/analysis.md).

Two halves, both acceptance criteria:

1. **The self-gate** — every checked-in contract under ``tests/contracts``
   must hold against the live repo: the CLI's ``--self-check --contracts``
   mode (which builds exactly the canonical program set the contracts were
   recorded from) exits 0 with zero drift.
2. **The gate has teeth** — seeded regressions must each fail it with the
   *specific* drifted-field finding: a step compiled with one extra
   deliberate all-gather (`collectives.all_gather.count`), and one with
   donation disabled (`donation.declared`). Plus the `--update-contracts`
   round-trip invariant: update → clean check → byte-identical JSON on the
   second update (contracts never churn when nothing drifted).

Byte fields in contracts carry percentage tolerances precisely so this file
can run on the CPU mesh without flaking on lowering differences; counts are
exact by design — one new collective is one new collective.
"""

import json
import os

import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.analysis import (
    ProgramContract,
    audit_lowered,
    drift_count,
    gate_reports,
)
from accelerate_tpu.analysis.contracts import update_contract
from accelerate_tpu.models import Bert

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONTRACTS_DIR = os.path.join(REPO_ROOT, "tests", "contracts")

# the programs the repo promises contracts for (ISSUE 8 acceptance): the
# bert steps, the llama FSDP step, the paged decode, every prefill span of
# the canonical self-check engine, and the bench-scale programs — plus the
# disaggregated-serving adopt/copy program (ISSUE 9: the per-page insert a
# live-KV handoff writes through must keep donation intact and no baked
# page-table constants)
REQUIRED_CONTRACTS = {
    "bert_tiny_step",
    "llama_tiny_fsdp_step",
    "serving_decode",
    # ISSUE 15: the kernel-enabled decode (Pallas page-walk attention) is a
    # different program with the same obligations — donation intact, page
    # tables as arguments — pinned under its own contract
    "serving_decode_kernels",
    "serving_prefill_16",
    "serving_prefill_32",
    "serving_prefill_64",
    "serving_adopt_kv",
    # speculative decoding: the windowed one-step verify program — donation
    # intact through the window widening, page tables and per-slot emit
    # limits as arguments (never baked)
    "serving_speculative_verify",
    "bert_base_step",
    "llama_125m_fsdp_step",
    # ISSUE 16: the redistribution primitive's chunk-commit stage program —
    # destination donated (one chunk in flight), peak HBM gated against the
    # scratch-bound-derived budget, no baked constants
    "redistribute_stage",
}


def _bert_accelerator():
    # the ONE canonical construction the bert_tiny_step contract is recorded
    # from — shared with the CLI self-check so the seeded regressions below
    # gate exactly the program the contract pins
    from accelerate_tpu.commands.analyze import canonical_bert_program

    return canonical_bert_program()


# -- the self-gate (acceptance criterion) --------------------------------------


def test_required_contracts_are_checked_in():
    present = {
        os.path.splitext(f)[0]
        for f in os.listdir(CONTRACTS_DIR)
        if f.endswith(".json")
    }
    missing = REQUIRED_CONTRACTS - present
    assert not missing, f"contracts missing from tests/contracts: {sorted(missing)}"
    # the concurrency contract is its own shape (ConcurrencyContract — exact
    # lock inventory, not per-program audit expectations); everything else
    # must load as a ProgramContract
    present.discard("concurrency")
    for name in sorted(present):
        contract = ProgramContract.load(os.path.join(CONTRACTS_DIR, f"{name}.json"))
        assert contract.program == name
        assert "max_errors" in contract.expectations
        assert contract.env.get("backend")


def test_self_gate_cli_contracts_pass_clean(capsys):
    """`accelerate-tpu analyze --self-check --contracts` over the repo's own
    checked-in contracts: zero drift, exit 0. This is the differential gate
    every later PR (the ZeRO/overlap work first) must keep green or update
    in a reviewed diff."""
    from accelerate_tpu.commands.cli import main

    rc = main(["analyze", "--self-check", "--contracts", "--contracts-dir", CONTRACTS_DIR])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "CONTRACT_DRIFT" not in out
    assert "CONTRACT_MISSING" not in out


# -- seeded regressions (the gate has teeth) -----------------------------------


def test_seeded_extra_all_gather_fails_gate():
    """One deliberate extra all-gather — a replicated copy of a data-sharded
    activation, exactly the shape of a sharding regression — must fail the
    bert contract with a finding naming collectives.all_gather.count. The
    canonical step is the ZeRO manual-region program, so the leak is an
    explicit gather over the data axis (a with_sharding_constraint inside a
    fully-manual region is a no-op by design)."""
    accelerator, model, batch = _bert_accelerator()
    base = Bert.loss_fn(model)

    def loss_with_gather(params, b):
        leak = jax.lax.all_gather(
            b["input_ids"].astype(jnp.float32), "data", axis=0, tiled=True
        )
        return base(params, b) + 0.0 * leak.sum()

    report = accelerator.analyze(
        loss_with_gather, batch, label="bert_tiny_step", write_record=False
    )
    findings = gate_reports([report], CONTRACTS_DIR)
    assert drift_count(findings) >= 1, [str(f) for f in findings]
    gather_drift = [
        f
        for f in findings
        if f.code == "CONTRACT_DRIFT"
        and f.data.get("field") == "collectives.all_gather.count"
    ]
    assert gather_drift, [str(f) for f in findings]
    assert gather_drift[0].severity == "error"  # ERROR findings exit 1 in the CLI
    # exactly one gather more than the contract pins (the ZeRO program's own
    # param gathers are part of the expectation; the leak is the +1)
    assert gather_drift[0].data["actual"] == gather_drift[0].data["expected"] + 1
    # the message names the expectation and the delta, for the PR author
    assert "collectives.all_gather.count" in gather_drift[0].message
    assert "(+1)" in gather_drift[0].message


def test_seeded_dropped_donation_fails_gate():
    """The same program compiled with donation off: the contract pins 76
    donated-and-aliased buffers, so donation.declared/aliased both drift."""
    accelerator, model, batch = _bert_accelerator()
    step = accelerator.compiled_step(Bert.loss_fn(model), donate=False)
    assert step.donate_argnums == ()
    report = accelerator.analyze(
        step=step, batch=batch, label="bert_tiny_step", write_record=False
    )
    findings = gate_reports([report], CONTRACTS_DIR)
    drifted_fields = {
        f.data.get("field") for f in findings if f.code == "CONTRACT_DRIFT"
    }
    assert "donation.declared" in drifted_fields, [str(f) for f in findings]
    assert "donation.aliased" in drifted_fields
    assert drift_count(findings) >= 2


def test_gate_exits_1_on_tampered_contract(tmp_path, capsys):
    """End-to-end CLI exit code: against a contracts dir whose bert contract
    expects a donation count the live program cannot produce, the gate must
    exit 1 and print the drifted field."""
    import shutil

    tampered_dir = tmp_path / "contracts"
    shutil.copytree(CONTRACTS_DIR, tampered_dir)
    path = tampered_dir / "bert_tiny_step.json"
    payload = json.loads(path.read_text())
    payload["expectations"]["donation"]["declared"] = 0
    payload["expectations"]["donation"]["aliased"] = 0
    path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")

    from accelerate_tpu.commands.cli import main

    # --no-compile keeps this fast: donation declaration is a lowering-level
    # property, so the tampered expectation still drifts without the AOT
    # compile (the memory/schedule sections degrade to warnings by design)
    rc = main(
        ["analyze", "--self-check", "--no-compile", "--contracts",
         "--contracts-dir", str(tampered_dir)]
    )
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "donation.declared" in out
    assert "CONTRACT_DRIFT" in out


# -- update round-trip ---------------------------------------------------------


def _tiny_report(label="tiny_prog"):
    def f(state, x):
        return state + x.sum(), state * 2.0

    lowered = jax.jit(f, donate_argnums=(0,)).lower(
        jnp.ones((32, 32)), jnp.ones((8,))
    )
    return audit_lowered(lowered, label=label)


def test_update_contracts_round_trip(tmp_path):
    """update → clean check → byte-identical JSON on the second update: the
    churn-free invariant that keeps contract diffs reviewable."""
    path = str(tmp_path / "tiny_prog.json")
    report = _tiny_report()
    assert update_contract(path, report) is True  # first write
    first = open(path, "rb").read()

    report2 = _tiny_report()  # fresh audit of the same program
    contract = ProgramContract.load(path)
    assert contract.check(report2) == []  # clean check between updates
    assert update_contract(path, report2) is False  # nothing drifted: no rewrite
    assert open(path, "rb").read() == first  # byte-identical

    # and a genuinely drifted program rewrites the file
    def g(state, x):
        return state + x.sum(), state * 2.0

    lowered = jax.jit(g).lower(jnp.ones((32, 32)), jnp.ones((8,)))  # no donation
    drifted = audit_lowered(lowered, label="tiny_prog", expect_donation=False)
    assert contract.check(drifted), "expected donation drift"
    assert update_contract(path, drifted) is True
    assert open(path, "rb").read() != first


def test_sub_report_drift_gates_the_root_report(tmp_path):
    """Drift in a merged sub-program (an engine prefill span, a fleet
    replica) must surface on the ROOT report — the root's errors are what
    the CLI exit code, the render, and the telemetry record read. merge()
    copies findings BEFORE the gate runs, so the gate must bubble its own
    findings up explicitly."""
    parent = _tiny_report("parent_prog")
    sub = _tiny_report("sub_prog")
    parent.merge(sub, prefix="sub")
    cdir = str(tmp_path)
    gate_reports([parent], cdir, update=True)  # write both contracts

    # tamper the SUB program's contract only
    path = os.path.join(cdir, "sub_prog.json")
    payload = json.loads(open(path).read())
    payload["expectations"]["donation"]["declared"] = 9
    open(path, "w").write(json.dumps(payload, indent=2, sort_keys=True) + "\n")

    fresh_parent = _tiny_report("parent_prog")
    fresh_parent.merge(_tiny_report("sub_prog"), prefix="sub")
    findings = gate_reports([fresh_parent], cdir)
    assert drift_count(findings) == 1
    root_drifts = [f for f in fresh_parent.errors if f.code == "CONTRACT_DRIFT"]
    assert root_drifts, "sub-program drift never reached the root report"
    assert root_drifts[0].data["field"] == "donation.declared"
    # and a missing sub contract warns on the root too
    os.remove(path)
    fresh = _tiny_report("parent_prog")
    fresh.merge(_tiny_report("sub_prog"), prefix="sub")
    gate_reports([fresh], cdir)
    assert any(f.code == "CONTRACT_MISSING" for f in fresh.findings)


def test_lowered_only_report_degrades_compiled_contract_to_warning(tmp_path):
    """A compiled contract checked against a lowered-only report must NOT
    fabricate drift from the compiled-only sections (the pre-GSPMD
    collective inventory is a different object than the executable's): one
    WARNING names them unchecked, donation and errors still gate."""
    path = str(tmp_path / "tiny_prog.json")
    compiled_report = _tiny_report()
    assert compiled_report.meta.get("compiled") is True
    update_contract(path, compiled_report)
    contract = ProgramContract.load(path)
    assert contract.compiled

    def f(state, x):
        return state + x.sum(), state * 2.0

    lowered_only = audit_lowered(
        jax.jit(f, donate_argnums=(0,)).lower(jnp.ones((32, 32)), jnp.ones((8,))),
        label="tiny_prog",
        compile=False,
    )
    findings = contract.check(lowered_only)
    assert drift_count(findings) == 0, [str(f) for f in findings]
    warnings = [f for f in findings if f.severity == "warning"]
    assert len(warnings) == 1 and warnings[0].data["field"] == "compiled"
    # ...and an update from the lowered-only report REFUSES to clobber the
    # compiled contract's sections it cannot re-derive
    before = open(path, "rb").read()
    assert update_contract(path, lowered_only) is False
    assert open(path, "rb").read() == before


def test_root_max_errors_excludes_sub_program_findings(tmp_path):
    """A sub-program's ERROR (say a prefill span's FP64_LEAK) gates via the
    SUB's contract; the root's max_errors check must not double-report it as
    root drift — the author would be pointed at the wrong program."""
    from accelerate_tpu.analysis import Finding

    clean_parent = _tiny_report("parent_prog")
    contract = ProgramContract.from_report(clean_parent)

    parent = _tiny_report("parent_prog")
    sub = _tiny_report("sub_prog")
    sub.add(Finding("FP64_LEAK", "seeded", severity="error", path="sub_prog"))
    parent.merge(sub, prefix="sub")
    assert parent.errors  # the merge copied the sub's error up
    findings = contract.check(parent)
    assert not any(
        f.data.get("field") == "errors" for f in findings
    ), [str(f) for f in findings]
    # while the sub's own contract still catches it
    sub_contract = ProgramContract.from_report(_tiny_report("sub_prog"))
    sub_findings = sub_contract.check(sub)
    assert any(f.data.get("field") == "errors" for f in sub_findings)


def test_update_refuses_section_loss(tmp_path):
    """A same-env report that simply lacks a pinned section (backend without
    memory_analysis) must not regenerate the contract — that would silently
    delete the peak-HBM expectations from the gate."""
    path = str(tmp_path / "tiny_prog.json")
    update_contract(path, _tiny_report())
    before = open(path, "rb").read()
    stripped = _tiny_report()
    stripped.inventory.pop("memory")
    assert update_contract(path, stripped) is False
    assert open(path, "rb").read() == before


def test_update_refuses_env_mismatch(tmp_path):
    """--update-contracts on the wrong environment must not silently rewrite
    a contract recorded elsewhere (that would turn the CI gate off: every
    check there would then CONTRACT_ENV_SKIPPED)."""
    path = str(tmp_path / "tiny_prog.json")
    report = _tiny_report()
    update_contract(path, report)
    contract = ProgramContract.load(path)
    contract.env = {"backend": "tpu", "num_devices": 256}
    contract.save(path)
    before = open(path, "rb").read()
    assert update_contract(path, report) is False
    assert open(path, "rb").read() == before


def test_contract_missing_and_env_skip(tmp_path):
    report = _tiny_report()
    # no contract checked in: the gate says so instead of passing silently
    findings = gate_reports([report], str(tmp_path))
    assert [f.code for f in findings] == ["CONTRACT_MISSING"]
    assert findings[0].severity == "warning"

    # a contract recorded on a different environment skips with INFO — it
    # cannot distinguish drift from device-count arithmetic
    contract = ProgramContract.from_report(report)
    contract.env = {"backend": "tpu", "num_devices": 256}
    skipped = contract.check(report)
    assert [f.code for f in skipped] == ["CONTRACT_ENV_SKIPPED"]
    assert skipped[0].severity == "info"


def test_contract_byte_tolerance_scales():
    report = _tiny_report()
    contract = ProgramContract.from_report(report)
    # push a byte expectation 50% off a value big enough to clear the 1 KiB
    # slack floor: the default 25% tolerance drifts...
    peak = report.inventory["memory"]["peak_hbm_bytes"]
    assert peak > 2048, "tiny program too tiny for this test's arithmetic"
    contract.expectations["memory"]["peak_hbm_bytes"] = int(peak * 1.5)
    assert any(
        f.data.get("field") == "memory.peak_hbm_bytes" for f in contract.check(report)
    )
    # ...but a tolerance-scaled check (how the CPU gate absorbs lowering
    # differences) accepts it, while exact counts still never loosen
    assert contract.check(report, tolerance_scale=2.0) == []
    contract.expectations["donation"]["declared"] += 1
    assert any(
        f.data.get("field") == "donation.declared"
        for f in contract.check(report, tolerance_scale=100.0)
    )
