"""Failure detection & membership (resilience/membership.py; ISSUE 14).

The claims this file pins, each as a measured property rather than prose:

- **The drill** (acceptance) — a chaos heartbeat-SILENT host is *named* by
  the membership detector (no FaultPlan host probe configured at all), the
  elastic ladder runs to buddy recovery bit-equal the checkpoint-rung
  reference, a stale-epoch write from the "dead" host is rejected and
  recorded, and the revived host re-admits through a join record into a
  bit-exact ``regrow()`` — with ``{"kind": "membership"}`` records
  (including ``mttd_s``) in telemetry.jsonl.
- **The detector** — silence, step-stamp stall (wedged-in-a-collective),
  supervisor publication, and the self-reported hang each name the right
  host with the right reason, and a clean window names nobody (no false
  positives). Timeout semantics are the SAME :class:`SilenceDetector` the
  serving fleet's replica heartbeat rides (pinned on both consumers).
- **Epoch fencing** — every membership transition mints a monotonically
  increasing epoch; a zombie's write from a superseded epoch is refused
  (``StaleEpochError``), while a fenced-out host that was since re-admitted
  adopts the new epoch transparently.
- **The store** — filesystem backend round-trips atomically, and store I/O
  flake (the chaos ``io_failures`` leg aimed at ``membership_store``) is
  ridden out by the jittered ``STORE_RETRY`` policy.
- **Satellites** — ``request_shrink()`` resolves through the membership
  probe (and the no-probe warning now points at ``membership=``); the
  chaos env vars parse; ``handle_signals=True`` off the main thread
  degrades to a warning instead of refusing to construct;
  ``PartialState.rejoin()`` is a pure mesh rebuild under the single
  controller.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax
import optax

from accelerate_tpu import (
    Accelerator,
    ElasticConfig,
    FaultPlan,
    FilesystemStore,
    MembershipConfig,
    MembershipService,
    ResilienceConfig,
    StaleEpochError,
    TelemetryConfig,
)
from accelerate_tpu.models import Bert
from accelerate_tpu.resilience import RetryPolicy, SilenceDetector
from accelerate_tpu.resilience.membership import (
    EPOCH_KEY,
    publish_supervisor_loss,
)
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils.random import set_seed


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _bert_batch(model, n=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": np.asarray(
            rng.integers(0, model.config.vocab_size, (n, seq)), np.int32
        ),
        "attention_mask": np.ones((n, seq), np.int32),
        "labels": np.asarray(rng.integers(0, 2, (n,)), np.int32),
    }


def _tree_equal(a, b) -> bool:
    return all(jax.tree.leaves(jax.tree.map(np.array_equal, a, b)))


def _gather(tree):
    return jax.tree.map(np.asarray, tree)


def _build(fault_plan=None, telemetry_dir=None, seed=0):
    _reset()
    set_seed(seed)
    accelerator = Accelerator(
        resilience_config=(
            ResilienceConfig(guard=None, fault_plan=fault_plan)
            if fault_plan is not None
            else None
        ),
        telemetry_config=TelemetryConfig(dir=telemetry_dir) if telemetry_dir else None,
    )
    model = Bert("bert-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    return accelerator, model, prepared, optimizer


def _records(telemetry_dir, kind):
    path = os.path.join(telemetry_dir, "telemetry.jsonl")
    with open(path) as f:
        return [r for r in map(json.loads, f) if r.get("kind") == kind]


# ---------------------------------------------------------------------------
# the shared silence primitive (fleet heartbeat + membership, one semantic)
# ---------------------------------------------------------------------------


def test_silence_detector_shared_semantics():
    """Strictly-greater-than-timeout, None disables — the ONE semantic both
    the serving fleet heartbeat and the membership detector ride."""
    detector = SilenceDetector(timeout_s=1.0)
    assert not detector.expired(last_seen=10.0, now=11.0)  # exactly timeout: alive
    assert detector.expired(last_seen=10.0, now=11.001)
    assert detector.silent_for(10.0, now=11.5) == pytest.approx(1.5)
    assert not SilenceDetector(timeout_s=None).expired(last_seen=0.0, now=1e9)


def test_fleet_heartbeat_rides_shared_detector():
    """The serving replica probe consumes SilenceDetector (no drift): a busy
    replica is dead strictly past the timeout, an idle one never is."""
    from accelerate_tpu.serving.fleet import EngineReplica, HealthPolicy

    class _Engine:
        busy = True

        class stats:
            watchdog_trips = 0
            slot_quarantines = 0

    replica = EngineReplica(0, _Engine(), policy=HealthPolicy(heartbeat_timeout_s=0.05))
    assert replica.heartbeat()
    replica.last_progress = time.monotonic() - 0.2
    assert not replica.heartbeat()
    _Engine.busy = False  # idle replicas are merely idle, never silent
    assert replica.heartbeat()


# ---------------------------------------------------------------------------
# store: atomic round-trip + flake ridden out by the retry policy
# ---------------------------------------------------------------------------


def test_filesystem_store_roundtrip(tmp_path):
    store = FilesystemStore(str(tmp_path))
    assert store.read("hosts/0") is None
    store.write("hosts/0", {"host": 0, "beat": 1})
    assert store.read("hosts/0") == {"host": 0, "beat": 1}
    store.write("hosts/1", {"host": 1, "beat": 2})
    listed = store.list("hosts")
    assert set(listed) == {"hosts/0", "hosts/1"}
    store.delete("hosts/0")
    assert store.read("hosts/0") is None
    store.delete("hosts/0")  # idempotent
    # a torn record reads as absent, never as fabricated state
    (tmp_path / "hosts" / "2.json").write_text('{"host": 2, "bea')
    assert store.read("hosts/2") is None


def test_store_io_flake_ridden_out_by_retry(tmp_path):
    """The chaos ``io_failures`` leg aimed at ``membership_store``: injected
    transient EIOs are absorbed by the store's jittered retry policy — the
    write lands, and the chaos ledger shows the faults really fired."""
    from accelerate_tpu.resilience import chaos as chaos_mod

    plan = chaos_mod.activate(FaultPlan(io_failures=2))
    try:
        store = FilesystemStore(
            str(tmp_path),
            retry_policy=RetryPolicy(base_delay=0.0, max_delay=0.0, jitter=0.0),
        )
        store.write("hosts/0", {"host": 0})
        assert store.read("hosts/0") == {"host": 0}
        assert sum(1 for e in plan.events if e["fault"] == "io_error") == 2
    finally:
        chaos_mod.deactivate()


# ---------------------------------------------------------------------------
# the failure detector: silence / step-stall / supervisor / hang, no FPs
# ---------------------------------------------------------------------------


def _service(tmp_path, sub="store", **config):
    defaults = dict(heartbeat_timeout_s=0.5, stall_steps_behind=2, stall_timeout_s=0.5)
    defaults.update(config)
    return MembershipService(
        FilesystemStore(str(tmp_path / sub)),
        num_hosts=2,
        config=MembershipConfig(**defaults),
    )


def test_detector_names_silent_host(tmp_path):
    svc = _service(tmp_path)
    t0 = time.time()
    svc.heartbeat(1, host=0, now=t0)
    svc.heartbeat(1, host=1, now=t0)
    # host 1 goes silent; host 0 keeps beating
    svc.heartbeat(4, host=0, now=t0 + 1.0)
    detections = svc.detect(now=t0 + 1.0)
    assert [d["host"] for d in detections] == [1]
    assert detections[0]["reason"] == "heartbeat_silence"
    assert detections[0]["mttd_s"] == pytest.approx(1.0, abs=0.01)
    # detection repeats until resolved (a boundary that couldn't act may act
    # later), but the telemetry/ledger entry lands once
    assert [d["host"] for d in svc.detect(now=t0 + 1.1)] == [1]
    assert sum(1 for e in svc.events if e["event"] == "host_suspected") == 1


def test_detector_names_step_stalled_host(tmp_path):
    """Beats keep flowing but the step-stamp froze while peers advanced:
    a rank wedged in a collective — named by the stall leg, not silence."""
    svc = _service(tmp_path, heartbeat_timeout_s=30.0)
    t0 = time.time()
    svc.heartbeat(1, host=0, now=t0)
    svc.heartbeat(1, host=1, now=t0)
    svc.heartbeat(4, host=0, now=t0 + 1.0)  # peer advanced 3 steps
    svc.heartbeat(1, host=1, now=t0 + 1.0)  # alive, step frozen since t0
    detections = svc.detect(now=t0 + 1.0)
    assert [d["host"] for d in detections] == [1]
    assert detections[0]["reason"] == "step_stall"
    assert detections[0]["steps_behind"] == 3
    assert detections[0]["mttd_s"] == pytest.approx(1.0, abs=0.01)


def test_detector_clean_window_no_false_positives(tmp_path):
    """Hosts beating and advancing together are never suspected — the
    false-positive count the bench gates at 0."""
    svc = _service(tmp_path, heartbeat_timeout_s=0.2, stall_timeout_s=0.2)
    t0 = time.time()
    for step in range(1, 9):
        for host in (0, 1):
            svc.heartbeat(step, host=host, now=t0 + 0.1 * step)
        assert svc.detect(now=t0 + 0.1 * step) == []
    assert not any(e["event"] == "host_suspected" for e in svc.events)


def test_supervisor_published_loss_is_named(tmp_path):
    """pod-launch --elastic's store publication: the supervisor knew who
    died; the detector surfaces it with zero inference."""
    svc = _service(tmp_path)
    t0 = time.time()
    svc.heartbeat(1, host=0, now=t0)
    svc.heartbeat(1, host=1, now=t0)
    publish_supervisor_loss(svc.store, 1, "exit code 9")
    detections = svc.detect(now=t0 + 0.01)
    assert [d["host"] for d in detections] == [1]
    assert detections[0]["reason"] == "supervisor"
    assert detections[0]["mttd_s"] >= 0.0
    # resolving the loss clears the publication and fences the epoch
    epoch = svc.resolve_loss(1, reason="supervisor")
    assert epoch == 2
    assert svc.store.read("lost/1") is None
    assert svc.detect(now=t0 + 0.02) == []


def test_self_reported_hang_flag_surfaces_to_peers(tmp_path):
    """The CollectiveHangWatchdog escalation: a wedged host's stall flag is
    a named suspicion for PEERS, never self-conviction."""
    store_dir = tmp_path / "hang"
    wedged = MembershipService(FilesystemStore(str(store_dir)), num_hosts=2, host_index=1)
    peer = MembershipService(FilesystemStore(str(store_dir)), num_hosts=2, host_index=0)
    t0 = time.time()
    for host in (0, 1):
        peer.heartbeat(1, host=host, now=t0)
    wedged.report_self_stall(2.5)
    assert any(e["event"] == "collective_hang_suspected" for e in wedged.events)
    # the wedged host does not convict itself off its own flag
    assert wedged.detect(now=t0 + 0.01) == []
    detections = peer.detect(now=t0 + 0.01)
    assert [d["host"] for d in detections] == [1]
    assert detections[0]["reason"] == "collective_hang"
    assert detections[0]["hang_s"] == 2.5


def test_hang_watchdog_trips_on_blocked_step_and_retracts_on_completion(tmp_path):
    """The StepWatchdog seam does the reporting: a step blocked past the
    deadline is reported from the side thread WHILE the host thread is
    stuck — and when the step then completes after all (slow, not dead),
    the disarm RETRACTS the flag so peers don't reshard out a healthy
    host. A true hang never reaches disarm, so a real wedge keeps its flag."""
    from accelerate_tpu.resilience import CollectiveHangWatchdog

    svc = _service(tmp_path)
    watchdog = CollectiveHangWatchdog(svc, timeout_s=0.05)
    try:
        watchdog.arm()
        time.sleep(0.3)  # the "wedged collective"
        # mid-wedge: the flag is up, peers can see it
        assert svc.store.read("stall/0") is not None
        watchdog.disarm()  # the step completed: slow, not dead
    finally:
        watchdog.close()
    assert watchdog.trips == 1
    assert svc.store.read("stall/0") is None  # retracted
    assert any(e["event"] == "collective_hang_suspected" for e in svc.events)
    assert any(e["event"] == "collective_hang_cleared" for e in svc.events)


# ---------------------------------------------------------------------------
# epoch fencing: zombies rejected, returnees adopt, epochs monotone
# ---------------------------------------------------------------------------


def test_epoch_fencing_rejects_zombie_write(tmp_path):
    store_dir = str(tmp_path / "fence")
    survivor = MembershipService(FilesystemStore(store_dir), num_hosts=2, host_index=0)
    zombie = MembershipService(FilesystemStore(store_dir), num_hosts=2, host_index=1)
    assert survivor.epoch == 1 and zombie.epoch == 1
    assert zombie.heartbeat(3)
    survivor.resolve_loss(1)
    assert survivor.epoch == 2
    # the zombie resumes after its stall: its write carries epoch 1 against
    # a view at epoch 2 with it fenced OUT — refused, recorded, no state
    assert not zombie.heartbeat(4)
    assert zombie.stale_writes_rejected == 1
    assert any(e["event"] == "stale_epoch_write_rejected" for e in zombie.events)
    assert zombie.epoch == 1  # it did NOT silently adopt the new epoch
    # the raw store API raises the typed error
    with pytest.raises(StaleEpochError, match="epoch 1"):
        zombie.store.fenced_write("hosts/1", {"host": 1}, epoch=1)
    # re-admission: join → admit → the returnee's next beat adopts epoch 3
    zombie.announce_join()
    assert survivor.pending_joins() == [1]
    assert survivor.admit(1) == 3
    assert zombie.heartbeat(4)
    assert zombie.epoch == 3
    assert any(e["event"] == "epoch_adopted" for e in zombie.events)


def test_epoch_mint_refuses_concurrent_transition(tmp_path):
    """Two survivors racing to resolve the same loss: exactly one mint wins
    (the CAS shape a GCS/etcd backend makes transactional)."""
    store_dir = str(tmp_path / "race")
    a = MembershipService(FilesystemStore(store_dir), num_hosts=3, host_index=0)
    b = MembershipService(FilesystemStore(store_dir), num_hosts=3, host_index=1)
    a.resolve_loss(2)
    with pytest.raises(StaleEpochError):
        b.store.mint_epoch({"epoch": 2, "members": [0, 1]}, expected=1)
    view = a.view()
    assert view["epoch"] == 2 and view["members"] == [0, 1]


def test_resolve_loss_race_loser_adopts_winners_epoch(tmp_path):
    """Every survivor independently detects the same loss and resolves it:
    exactly one mint wins, and the LOSERS adopt the winner's epoch instead
    of erroring out of an otherwise-successful recovery."""
    store_dir = str(tmp_path / "race2")
    a = MembershipService(FilesystemStore(store_dir), num_hosts=3, host_index=0)
    b = MembershipService(FilesystemStore(store_dir), num_hosts=3, host_index=1)
    assert a.resolve_loss(2) == 2
    # b raced and lost (its view was epoch 1 when the loss happened): the
    # host is already gone, so b adopts epoch 2 — no raise, no double mint
    assert b.resolve_loss(2) == 2
    assert b.epoch == 2
    assert a.view()["epoch"] == 2  # not minted twice
    assert any(e["event"] == "epoch_adopted" for e in b.events)
    # same shape for admit: a admits the returnee, b's admit adopts
    a.announce_join(2)
    assert a.admit(2) == 3
    assert b.admit(2) == 3
    assert b.epoch == 3
    assert a.view()["members"] == [0, 1, 2]


def test_member_without_heartbeat_record_is_silent_from_epoch_mint(tmp_path):
    """A host admitted (its stale heartbeat record deliberately cleared)
    that dies before its FIRST beat must not be invisible: silence anchors
    on the epoch mint time."""
    svc = _service(tmp_path)  # heartbeat_timeout_s=0.5
    t0 = time.time()
    svc.heartbeat(1, host=0, now=t0)
    svc.heartbeat(1, host=1, now=t0)
    svc.resolve_loss(1)
    svc.announce_join(1)
    svc.admit(1)  # deletes hosts/1 — and host 1 dies before re-beating
    mint_time = svc.view()["minted_at"]
    svc.heartbeat(2, host=0, now=mint_time + 1.0)
    assert svc.detect(now=mint_time + 0.1) == []  # inside the mint grace
    detections = svc.detect(now=mint_time + 1.0)
    assert [d["host"] for d in detections] == [1]
    assert detections[0]["reason"] == "heartbeat_silence"
    assert detections[0]["never_beat"] is True
    assert detections[0]["mttd_s"] == pytest.approx(1.0, abs=0.01)


def test_multi_sequential_losses_epochs_increase_monotonically(tmp_path):
    """Loss after loss after re-admission: every transition mints the next
    epoch, strictly increasing — the property the zombie fence stands on."""
    svc = MembershipService(FilesystemStore(str(tmp_path / "seq")), num_hosts=4)
    epochs = [svc.epoch]
    epochs.append(svc.resolve_loss(3))
    epochs.append(svc.resolve_loss(1))
    svc.announce_join(3)
    epochs.append(svc.admit(3))
    epochs.append(svc.resolve_loss(2))
    assert epochs == [1, 2, 3, 4, 5]
    assert svc.view()["members"] == [0, 3]
    minted = [e for e in svc.events if e["event"] == "epoch_minted"]
    assert [e["epoch"] for e in minted] == [2, 3, 5]  # admit records host_admitted


# ---------------------------------------------------------------------------
# the acceptance drill: silent host NAMED (no FaultPlan host probe),
# ladder → buddy bit-equal the checkpoint reference, zombie fenced,
# join-record re-admission → bit-exact regrow
# ---------------------------------------------------------------------------


def _membership_coordinator(tmp_path, sub, fault_plan=None, redundancy=1, **svc_kwargs):
    tdir = str(tmp_path / f"telemetry_{sub}")
    accelerator, model, prepared, optimizer = _build(
        fault_plan=fault_plan, telemetry_dir=tdir
    )
    membership = MembershipService(
        FilesystemStore(str(tmp_path / f"store_{sub}")),
        num_hosts=2,
        config=MembershipConfig(
            heartbeat_timeout_s=0.1, stall_steps_behind=2, stall_timeout_s=0.1
        ),
        **svc_kwargs,
    )
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model),
        config=ElasticConfig(redundancy=redundancy, num_hosts=2),
        membership=membership,
    )
    return accelerator, model, prepared, optimizer, coordinator, tdir


def test_membership_drill_silent_host_named_recovers_readmits(tmp_path):
    # --- the drill: NO host_loss probe anywhere — the chaos leg only
    # silences host 1's heartbeat publisher from boundary 4 on; the
    # membership detector must do the naming
    plan = FaultPlan(membership_silence_step=4, membership_silence_index=1)
    assert plan.host_loss_step is None
    accelerator, model, prepared, optimizer, coordinator, tdir = _membership_coordinator(
        tmp_path, "drill", fault_plan=plan
    )
    membership = coordinator.membership
    batch = _bert_batch(model)
    losses = []
    for _ in range(3):
        losses.append(float(coordinator.step(batch)))
    # host 1's publisher is now dead; give the silence time to exceed the
    # detector timeout, then the next boundary must name it and recover
    time.sleep(0.15)
    zombie = MembershipService(
        FilesystemStore(str(tmp_path / "store_drill")), num_hosts=2, host_index=1
    )
    assert zombie.epoch == 1
    for _ in range(3):
        losses.append(float(coordinator.step(batch)))
    assert coordinator.last_recovery["event"] == "recovered"
    assert coordinator.last_recovery["rung"] == "buddy"
    assert coordinator.last_recovery["host"] == 1
    assert coordinator.last_recovery["steps_lost"] == 0
    assert coordinator.last_recovery["epoch"] == 2
    assert dict(coordinator.mesh.shape)["data"] == 4

    # --- bit-equal the checkpoint-rung reference on the same shrunken mesh
    # (the PR 12 reference pattern: chaos host_loss at the same boundary,
    # redundancy=0, checkpoint saved AT the boundary)
    ckpt_dir = str(tmp_path / "ref_ckpt")
    ref_plan = FaultPlan(host_loss_step=4, host_loss_index=1)
    acc_b, model_b, prep_b, opt_b = _build(
        fault_plan=ref_plan, telemetry_dir=str(tmp_path / "telemetry_ref")
    )
    coord_b = acc_b.elastic_coordinator(
        Bert.loss_fn(model_b),
        config=ElasticConfig(redundancy=0, num_hosts=2, checkpoint_dir=ckpt_dir),
    )
    batch_b = _bert_batch(model_b)
    losses_b = []
    for i in range(6):
        if coord_b.completed_steps == 3:
            acc_b.save_state(
                os.path.join(ckpt_dir, "checkpoint_3"), manifest_metadata={"step": 3}
            )
        losses_b.append(float(coord_b.step(batch_b)))
    assert coord_b.last_recovery["rung"] == "checkpoint"
    assert _tree_equal(_gather(prepared.params), _gather(prep_b.params))
    assert _tree_equal(_gather(optimizer.opt_state), _gather(opt_b.opt_state))
    np.testing.assert_array_equal(losses, losses_b)

    # --- the zombie: host 1 "comes back" holding the superseded epoch — its
    # write is rejected and recorded, never landed
    assert not zombie.heartbeat(99)
    assert zombie.stale_writes_rejected == 1

    # --- re-admission: join record → survivors pick it up at the next step
    # boundary and turn it into regrow(), bit-exact
    zombie.announce_join()
    losses.append(float(coordinator.step(batch)))  # boundary admits + regrows
    assert dict(coordinator.mesh.shape)["data"] == 8
    assert coordinator.lost_hosts == set()
    regrown = [r for r in coordinator.recoveries if r["event"] == "regrown"]
    assert len(regrown) == 1 and regrown[0]["hosts"] == [1]
    assert regrown[0]["epoch"] == 3
    assert membership.view()["members"] == [0, 1]
    # (regrow bit-exactness is pinned without a step in between by
    # test_membership_regrow_is_bit_exact_relayout)
    assert zombie.heartbeat(coordinator.completed_steps)  # re-adopts epoch 3
    assert zombie.epoch == 3

    # --- observability: membership records with mttd_s in telemetry.jsonl
    records = _records(tdir, "membership")
    events = [r["event"] for r in records]
    assert "host_suspected" in events
    suspected = next(r for r in records if r["event"] == "host_suspected")
    assert suspected["host"] == 1
    assert suspected["reason"] == "heartbeat_silence"
    assert suspected["mttd_s"] > 0.1  # at least the detector timeout
    minted = [r for r in records if r["event"] == "epoch_minted"]
    assert [r["epoch"] for r in minted] == [2]
    assert "host_admitted" in events
    # the elastic recovery record carries the epoch it minted
    recovered = [
        r for r in _records(tdir, "elastic") if r["event"] == "recovered"
    ]
    assert len(recovered) == 1 and recovered[0]["epoch"] == 2
    # the chaos ledger agrees the silence (and nothing else) fired
    faults = [e["fault"] for e in accelerator.resilience.chaos.events]
    assert faults == ["membership_silence"]


def test_membership_regrow_is_bit_exact_relayout(tmp_path):
    """The regrow-through-join path is a pure relayout: params/opt state
    gathered before the shrink, after the shrink, and after the join-driven
    regrow are all bit-identical when no step runs in between."""
    accelerator, model, prepared, optimizer, coordinator, _ = _membership_coordinator(
        tmp_path, "relayout"
    )
    batch = _bert_batch(model)
    for _ in range(2):
        coordinator.step(batch)
    reference = _gather(prepared.params)
    reference_opt = _gather(optimizer.opt_state)
    coordinator.reshard(lost_host=1)
    assert coordinator.membership.epoch == 2
    assert _tree_equal(reference, _gather(prepared.params))
    assert _tree_equal(reference_opt, _gather(optimizer.opt_state))
    # the revived host announces; the coordinator picks the join up at the
    # boundary WITHOUT stepping first (regrow precedes the step)
    joiner = MembershipService(
        FilesystemStore(str(tmp_path / "store_relayout")), num_hosts=2, host_index=1
    )
    joiner.announce_join()
    assert coordinator.membership.pending_joins() == [1]
    coordinator._membership_boundary()  # what step() runs first at a boundary
    assert dict(coordinator.mesh.shape)["data"] == 8
    assert coordinator.membership.epoch == 3
    assert _tree_equal(reference, _gather(prepared.params))
    assert _tree_equal(reference_opt, _gather(optimizer.opt_state))
    coordinator.step(batch)  # and the regrown mesh trains


def test_step_stall_straggler_drives_ladder(tmp_path):
    """The wedged-rank drill end to end: host 1 keeps heartbeating but its
    step-stamp freezes (chaos membership_stall); peers advance; the
    detector names it via the stall leg and the ladder recovers."""
    plan = FaultPlan(membership_stall_step=2, membership_stall_index=1)
    accelerator, model, prepared, optimizer, coordinator, tdir = _membership_coordinator(
        tmp_path, "stall", fault_plan=plan
    )
    batch = _bert_batch(model)
    for _ in range(3):
        coordinator.step(batch)
    assert coordinator.last_recovery is None  # not enough peer progress yet
    time.sleep(0.15)  # stall_timeout_s=0.1 since the stamp last advanced
    coordinator.step(batch)
    assert coordinator.last_recovery is not None
    assert coordinator.last_recovery["host"] == 1
    assert coordinator.last_recovery["rung"] == "buddy"
    suspected = next(
        r for r in _records(tdir, "membership") if r["event"] == "host_suspected"
    )
    assert suspected["reason"] == "step_stall"
    assert suspected["mttd_s"] > 0.1
    faults = [e["fault"] for e in accelerator.resilience.chaos.events]
    assert faults == ["membership_stall"]


# ---------------------------------------------------------------------------
# satellites: request_shrink via membership, env vars, signal-thread degrade,
# rejoin seam, coordinator validation
# ---------------------------------------------------------------------------


def test_request_shrink_resolves_via_membership_probe(tmp_path):
    """Satellite branch A: a supervisor-published loss + SIGUSR1-style
    request_shrink() resolves to a NAMED reshard — no chaos host probe, no
    warning."""
    accelerator, model, prepared, optimizer, coordinator, tdir = _membership_coordinator(
        tmp_path, "resolve"
    )
    batch = _bert_batch(model)
    coordinator.step(batch)
    publish_supervisor_loss(coordinator.membership.store, 1, "exit code 3")
    coordinator.request_shrink()
    coordinator.step(batch)
    assert coordinator.last_recovery["event"] == "recovered"
    assert coordinator.last_recovery["host"] == 1
    assert dict(coordinator.mesh.shape)["data"] == 4
    assert not any(
        r["event"] == "shrink_request_unresolved" for r in _records(tdir, "elastic")
    )
    suspected = next(
        r for r in _records(tdir, "membership") if r["event"] == "host_suspected"
    )
    assert suspected["reason"] == "supervisor"


def test_request_shrink_without_probe_warning_points_at_membership(tmp_path, caplog):
    """Satellite branch B: with NO membership probe the PR 12 warning +
    record are kept — and the warning now tells the operator about
    membership=."""
    import logging

    tdir = str(tmp_path / "telemetry")
    accelerator, model, prepared, optimizer = _build(telemetry_dir=tdir)
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model), config=ElasticConfig(redundancy=0, num_hosts=2)
    )
    assert coordinator.membership is None
    batch = _bert_batch(model)
    coordinator.step(batch)
    coordinator.request_shrink()
    with caplog.at_level(logging.WARNING):
        coordinator.step(batch)
    warning = next(r.message for r in caplog.records if "no host probe" in r.message)
    assert "membership=" in warning
    assert any(
        r["event"] == "shrink_request_unresolved" for r in _records(tdir, "elastic")
    )
    assert dict(coordinator.mesh.shape)["data"] == 8  # run continues, full mesh


def test_membership_chaos_env_vars(monkeypatch):
    monkeypatch.setenv("ACCELERATE_CHAOS_MEMBERSHIP_SILENCE_STEP", "4")
    monkeypatch.setenv("ACCELERATE_CHAOS_MEMBERSHIP_SILENCE_INDEX", "1")
    monkeypatch.setenv("ACCELERATE_CHAOS_MEMBERSHIP_STALL_STEP", "6")
    monkeypatch.setenv("ACCELERATE_CHAOS_MEMBERSHIP_STALL_INDEX", "2")
    plan = FaultPlan.from_env()
    assert plan is not None and plan.active
    # silence is PERSISTENT from the armed boundary (a dead publisher never
    # beats again), recorded once
    assert not plan.membership_silent(1, 3)
    assert not plan.membership_silent(0, 4)
    assert plan.membership_silent(1, 4)
    assert plan.membership_silent(1, 7)
    assert sum(1 for e in plan.events if e["fault"] == "membership_silence") == 1
    # the stall publishes the frozen pre-wedge step-stamp
    assert plan.membership_stall(2, 5) is None
    assert plan.membership_stall(2, 6) == 5
    assert plan.membership_stall(2, 9) == 5
    assert sum(1 for e in plan.events if e["fault"] == "membership_stall") == 1


def test_handle_signals_off_main_thread_degrades_to_warning(tmp_path, caplog):
    """Satellite: a library-embedded coordinator (constructed off the main
    thread) cannot install the SIGUSR1 handler — it must still construct,
    warning once, with the handler flagged unarmed."""
    import logging

    accelerator, model, prepared, optimizer = _build(
        telemetry_dir=str(tmp_path / "telemetry")
    )
    result = {}

    def construct():
        with caplog.at_level(logging.WARNING):
            result["coordinator"] = accelerator.elastic_coordinator(
                Bert.loss_fn(model),
                config=ElasticConfig(redundancy=0, num_hosts=2, handle_signals=True),
            )

    thread = threading.Thread(target=construct)
    thread.start()
    thread.join()
    coordinator = result["coordinator"]  # constructed, no raise
    assert not coordinator.signals_armed
    assert any("UNARMED" in r.message for r in caplog.records)
    # the manual path still works
    coordinator.request_shrink()
    assert coordinator._shrink_requested
    # and ON the main thread the handler arms
    accelerator2, model2, _, _ = _build(telemetry_dir=str(tmp_path / "t2"))
    armed = accelerator2.elastic_coordinator(
        Bert.loss_fn(model2),
        config=ElasticConfig(redundancy=0, num_hosts=2, handle_signals=True),
    )
    assert armed.signals_armed


def test_late_watchdog_trip_after_disarm_is_suppressed(tmp_path):
    """The disarm/trip race: a watchdog thread firing AFTER the step
    completed (disarm already ran) must not publish an orphaned stall flag
    nobody will ever retract — peers would reshard out a healthy host."""
    from accelerate_tpu.resilience import CollectiveHangWatchdog

    svc = _service(tmp_path)
    watchdog = CollectiveHangWatchdog(svc, timeout_s=60.0)  # will not trip on its own
    try:
        watchdog.arm()
        watchdog.disarm()
        # the preempted thread fires late, after disarm
        watchdog._on_hang(0.5)
    finally:
        watchdog.close()
    assert watchdog.trips == 0
    assert svc.store.read("stall/0") is None
    assert not any(e["event"] == "collective_hang_suspected" for e in svc.events)


def test_host_index_out_of_range_raises():
    """Clamping would alias several processes onto one membership identity
    (their interleaved beats mask a real death) — reject loudly instead."""
    import tempfile

    with pytest.raises(ValueError, match="host_index"):
        MembershipService(
            FilesystemStore(tempfile.mkdtemp()), num_hosts=2, host_index=2
        )


def test_store_outage_degrades_boundary_instead_of_killing_run(tmp_path, caplog):
    """Store weather outlasting STORE_RETRY must not crash the training run
    the membership service exists to protect: the boundary's membership
    work degrades to a warning + record and the step still executes."""
    import logging

    accelerator, model, prepared, optimizer, coordinator, tdir = _membership_coordinator(
        tmp_path, "outage"
    )
    batch = _bert_batch(model)
    coordinator.step(batch)
    broken = coordinator.membership.store

    def _raise(*args, **kwargs):
        raise OSError(5, "mount gone")

    for op in ("read", "write", "list", "delete"):
        setattr(broken, op, _raise)
    with caplog.at_level(logging.WARNING):
        loss = float(coordinator.step(batch))  # survives the outage
    assert np.isfinite(loss)
    assert coordinator.completed_steps == 2
    assert any("degraded" in r.message for r in caplog.records)
    assert any(e["event"] == "store_degraded" for e in coordinator.membership.events)


def test_min_probe_interval_throttles_store_io_but_not_requests(tmp_path):
    """Per-boundary store I/O is throttled by min_probe_interval_s (a pod
    with sub-second steps must not fsync per step) — while an explicit
    request_shrink() probes immediately regardless."""
    accelerator, model, prepared, optimizer = _build(
        telemetry_dir=str(tmp_path / "telemetry")
    )
    store = FilesystemStore(str(tmp_path / "store"))
    membership = MembershipService(
        store,
        num_hosts=2,
        config=MembershipConfig(heartbeat_timeout_s=86400.0, min_probe_interval_s=3600.0),
    )
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model),
        config=ElasticConfig(redundancy=1, num_hosts=2),
        membership=membership,
    )
    batch = _bert_batch(model)
    for _ in range(3):
        coordinator.step(batch)
    # the first boundary beat; the next two were inside the interval
    assert store.read("hosts/0")["beat"] == 1
    # an explicit supervisor signal probes NOW despite the throttle — and
    # runs the full boundary (fresh beats published) before detecting
    publish_supervisor_loss(store, 1, "exit code 9")
    coordinator.request_shrink()
    coordinator.step(batch)
    assert coordinator.last_recovery is not None
    assert coordinator.last_recovery["host"] == 1
    assert store.read("hosts/0")["beat"] == 2  # the requested boundary beat


def test_probe_interval_must_sit_under_heartbeat_timeout():
    """An interval at or past the timeout would read healthy peers (whose
    beats age up to one interval between probes) as silent — rejected at
    config time."""
    with pytest.raises(ValueError, match="min_probe_interval_s"):
        MembershipConfig(heartbeat_timeout_s=30.0, min_probe_interval_s=30.0)
    MembershipConfig(heartbeat_timeout_s=30.0, min_probe_interval_s=7.5)  # fine
    # None disables the silence leg entirely — no false-positive hazard for
    # the throttle to guard against, so the combination is legal
    MembershipConfig(heartbeat_timeout_s=None, min_probe_interval_s=5.0)


def test_multi_process_coordinator_publishes_only_its_own_heartbeat(tmp_path):
    """On a real pod every process must publish ONLY its own beat: peers
    refreshing a dead host's record would blind the silence detector. The
    sim flag (process_count==1) is what enables publish-for-all."""
    accelerator, model, prepared, optimizer, coordinator, _ = _membership_coordinator(
        tmp_path, "ownbeat"
    )
    assert coordinator._sim_publish  # single controller: simulate all hosts
    store = coordinator.membership.store
    batch = _bert_batch(model)
    coordinator.step(batch)
    assert store.read("hosts/0") is not None and store.read("hosts/1") is not None
    # flip to the real-pod publishing discipline: only host_index beats
    coordinator._sim_publish = False
    store.delete("hosts/1")
    coordinator.step(batch)
    assert store.read("hosts/0")["beat"] == 2
    assert store.read("hosts/1") is None  # nobody resurrects the peer's record


def test_resolve_loss_store_outage_degrades_not_unwinds_recovery(tmp_path):
    """Store weather at the epoch mint — the moment right AFTER a
    successful in-memory recovery — must degrade, never crash the job the
    ladder just saved."""
    accelerator, model, prepared, optimizer, coordinator, _ = _membership_coordinator(
        tmp_path, "mintfail"
    )
    batch = _bert_batch(model)
    for _ in range(2):
        coordinator.step(batch)
    membership = coordinator.membership

    def _raise(*args, **kwargs):
        raise OSError(5, "mount gone")

    membership.store.write = _raise  # the mint's write path
    report = coordinator.reshard(lost_host=1)  # recovery itself succeeds
    assert report["rung"] == "buddy"
    assert "epoch" not in report  # honestly absent, not fabricated
    assert dict(coordinator.mesh.shape)["data"] == 4
    assert any(e["event"] == "store_degraded" for e in membership.events)


def test_stale_join_records_resolve_instead_of_looping(tmp_path):
    """A join record the coordinator cannot regrow (host never lost from
    ITS mesh) must not re-list forever: a moot record (already a member) is
    deleted, a genuinely fenced-out joiner is admitted at the membership
    level."""
    accelerator, model, prepared, optimizer, coordinator, _ = _membership_coordinator(
        tmp_path, "stalejoin"
    )
    membership = coordinator.membership
    batch = _bert_batch(model)
    # moot join: host 1 is a live member and was never lost
    membership.announce_join(1)
    coordinator.step(batch)
    assert membership.pending_joins() == []
    assert membership.view()["members"] == [0, 1]
    # fenced-out joiner with no coordinator memory of the loss (restart
    # scenario): membership resolved it out, lost_hosts is empty
    membership.resolve_loss(1, reason="pre_restart")
    epoch_before = membership.epoch
    joiner = MembershipService(
        FilesystemStore(str(tmp_path / "store_stalejoin")), num_hosts=2, host_index=1
    )
    joiner.announce_join()
    coordinator.step(batch)  # admits at the membership level, no regrow needed
    assert membership.pending_joins() == []
    assert membership.view()["members"] == [0, 1]
    assert membership.epoch == epoch_before + 1


def test_membership_from_env_wires_unmodified_coordinator(tmp_path, monkeypatch):
    """The pod-launch transport: ACCELERATE_MEMBERSHIP_DIR alone gives an
    unmodified training script's coordinator a live membership probe —
    supervisor publications resolve without any code change."""
    store_dir = str(tmp_path / "env_store")
    monkeypatch.setenv("ACCELERATE_MEMBERSHIP_DIR", store_dir)
    accelerator, model, prepared, optimizer = _build(
        telemetry_dir=str(tmp_path / "telemetry")
    )
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model), config=ElasticConfig(redundancy=1, num_hosts=2)
    )
    assert coordinator.membership is not None
    assert isinstance(coordinator.membership.store, FilesystemStore)
    assert coordinator.membership.store.root == store_dir
    batch = _bert_batch(model)
    coordinator.step(batch)
    publish_supervisor_loss(store_dir, 1, "silent for 30s")
    coordinator.request_shrink()  # the SIGUSR1 the supervisor sent
    coordinator.step(batch)
    assert coordinator.last_recovery["host"] == 1
    assert coordinator.last_recovery["rung"] == "buddy"


def test_rejoin_seam_is_pure_rebuild_under_single_controller():
    """PartialState.rejoin without ACCELERATE_ELASTIC_REAL_REJOIN is exactly
    rebuild_mesh — the simulation boundary, pinned (the real-pod
    jax.distributed path is env-gated and documented, not reachable on
    CPU)."""
    import dataclasses as dc

    _reset()
    accelerator = Accelerator()
    state = accelerator.state._partial
    devices = list(state.mesh.devices.reshape(-1))[:4]
    par = dc.replace(state.parallelism, data=4)
    mesh = state.rejoin(devices=devices, parallelism=par)
    assert mesh is state.mesh
    assert mesh.devices.size == 4
    full = state.rejoin(
        devices=list(jax.devices()), parallelism=dc.replace(par, data=8)
    )
    assert full.devices.size == 8


def test_coordinator_rejects_mismatched_membership_view(tmp_path):
    """A membership service tracking a different host count than the
    coordinator simulates would name different hosts for the same rank —
    refused at construction."""
    accelerator, model, prepared, optimizer = _build(
        telemetry_dir=str(tmp_path / "telemetry")
    )
    membership = MembershipService(
        FilesystemStore(str(tmp_path / "store")), num_hosts=4
    )
    with pytest.raises(ValueError, match="4 hosts"):
        accelerator.elastic_coordinator(
            Bert.loss_fn(model),
            config=ElasticConfig(redundancy=0, num_hosts=2),
            membership=membership,
        )


def test_coordinator_hang_watchdog_reports_wedged_step(tmp_path):
    """The coordinator arms the hang watchdog around the compiled step: a
    step blocked past the deadline is reported from the side (record + store
    stall flag) while the run eventually completes."""
    accelerator, model, prepared, optimizer = _build(
        telemetry_dir=str(tmp_path / "telemetry_hang")
    )
    membership = MembershipService(
        FilesystemStore(str(tmp_path / "store_hang")),
        num_hosts=2,
        config=MembershipConfig(
            heartbeat_timeout_s=30.0, hang_watchdog_timeout_s=0.05
        ),
    )
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model),
        config=ElasticConfig(redundancy=0, num_hosts=2),
        membership=membership,
    )
    assert coordinator._hang_watchdog is not None
    real_step = coordinator._step

    def slow_step(batch):
        time.sleep(0.3)  # the wedge
        return real_step(batch)

    coordinator._step = slow_step
    coordinator.step(_bert_batch(model))
    assert coordinator._hang_watchdog.trips == 1
    # the step COMPLETED, so the flag was retracted on disarm — a slow step
    # must not leave the host permanently convicted
    assert membership.store.read("stall/0") is None
    records = _records(str(tmp_path / "telemetry_hang"), "membership")
    assert any(r["event"] == "collective_hang_suspected" for r in records)
    assert any(r["event"] == "collective_hang_cleared" for r in records)


def test_dictstore_is_a_dropin_membership_backend():
    """The in-memory CAS store (ISSUE 16) carries a full membership
    lifecycle — heartbeats, loss resolution, zombie fencing, re-admission —
    identically to FilesystemStore: nothing above the store changes."""
    from accelerate_tpu import DictStore

    store = DictStore()
    a = MembershipService(store, num_hosts=2, host_index=0)
    b = MembershipService(store, num_hosts=2, host_index=1)
    assert a.heartbeat(1) and b.heartbeat(1)
    assert a.resolve_loss(1) == 2
    # the zombie's stale write is refused by the real CAS, not a race
    assert not b.heartbeat(2)
    assert b.stale_writes_rejected == 1
    with pytest.raises(StaleEpochError):
        store.fenced_write("hosts/1", {"host": 1}, epoch=1)
    b.announce_join()
    assert a.pending_joins() == [1]
    assert a.admit(1) == 3
    assert b.heartbeat(2) and b.epoch == 3
