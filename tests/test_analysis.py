"""Program analyzer: self-gate + seeded regressions.

The self-gate is the acceptance invariant: the analyzer runs over the repo's
OWN compiled step (bert-tiny) and serving decode programs and must report
zero ERROR findings — donation intact, no fp64 leaks, no warm-loop hazards.
The seeded-regression tests prove the gate has teeth: a deliberately broken
donation, an injected ``.item()`` host sync, and a shape-bucket recompile
must each be caught.

All tier-1-fast on the CPU mesh: donation markers, collective inventories,
and jit-cache events are backend-independent properties of the programs.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu import Accelerator
from accelerate_tpu.analysis import (
    CATALOG,
    AnalysisReport,
    Finding,
    HazardSanitizer,
    audit_lowered,
    collective_inventory,
    donation_drop_warning,
    explain_recompile,
    lint_paths,
    lint_source,
    signature_of,
)
from accelerate_tpu.models import Bert, Llama
from accelerate_tpu.serving import ServingEngine
from accelerate_tpu.telemetry import TelemetryConfig

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bert_batch(model, batch_size=8, seq_len=16, sharding=None, seed=0):
    rng = np.random.default_rng(seed)
    batch = {
        "input_ids": jnp.asarray(
            rng.integers(0, model.config.vocab_size, (batch_size, seq_len)), jnp.int32
        ),
        "attention_mask": jnp.ones((batch_size, seq_len), jnp.int32),
        "labels": jnp.asarray(rng.integers(0, 2, (batch_size,)), jnp.int32),
    }
    if sharding is not None:
        batch = {k: jax.device_put(v, sharding) for k, v in batch.items()}
    return batch


# -- the self-gate (acceptance criterion) ------------------------------------


def test_self_gate_compiled_step_zero_errors(tmp_path):
    """The repo's own fused step program must audit clean: every donated
    buffer aliased, no fp64, no oversized constants — and the report must
    land as a {"kind": "analysis"} record in telemetry.jsonl."""
    accelerator = Accelerator(telemetry_config=TelemetryConfig(dir=str(tmp_path)))
    model = Bert("bert-tiny")
    accelerator.prepare_model(model)
    accelerator.prepare_optimizer(optax.adamw(1e-4))
    batch = _bert_batch(model, sharding=accelerator.state.data_sharding())

    report = accelerator.analyze(Bert.loss_fn(model), batch)
    assert report.errors == [], report.render()
    donation = report.inventory["donation"]
    assert donation["declared"] > 0
    assert donation["aliased"] == donation["declared"]
    # the data-parallel grad sync is visible as a diffable collective inventory
    collectives = report.inventory["collectives"]
    assert collectives.get("all_reduce", {}).get("count", 0) >= 1
    assert collectives["all_reduce"]["bytes"] > 0
    # executable-level confirmation: XLA kept the aliases
    assert donation.get("executable_alias_entries", 0) == donation["declared"]
    assert donation.get("alias_bytes", 0) > 0
    accelerator.telemetry.finish()
    records = [
        json.loads(line) for line in open(tmp_path / "telemetry.jsonl", encoding="utf-8")
    ]
    analysis = [r for r in records if r["kind"] == "analysis"]
    assert analysis and analysis[0]["analysis"]["counts"]["error"] == 0


def test_self_gate_serving_decode_zero_errors():
    model = Llama("llama-tiny")
    engine = ServingEngine(model, model.init(jax.random.key(0)), num_slots=2, max_len=32)
    report = engine.analyze(write_record=False)
    assert report.errors == [], report.render()
    # on CPU donation is off by backend string — the audit says so explicitly
    assert any(f.code == "DONATION_DISABLED" for f in report.findings)
    # prefill programs audited too (lowered-only)
    assert any(k.startswith("prefill_") for k in report.inventory)


# -- seeded regressions (the gate has teeth) ----------------------------------


def test_seeded_broken_donation_is_caught():
    """Donate a buffer that cannot alias any output: the analyzer must name
    it. This is exactly the silent failure mode donate_argnums has today."""

    def broken(params, batch):
        return batch.sum() + params.sum()  # params donated, only scalars out

    lowered = jax.jit(broken, donate_argnums=(0,)).lower(
        jnp.ones((64, 64)), jnp.ones((4,))
    )
    report = audit_lowered(lowered, label="seeded_broken")
    assert [f.code for f in report.errors] == ["DONATION_DROPPED"]
    assert report.inventory["donation"]["aliased"] < report.inventory["donation"]["declared"]


def test_executable_level_donation_drop_reaches_report():
    """Donation can survive lowering (jax.buffer_donor) and still be dropped
    by XLA (sharding/layout mismatch). audit_lowered must surface the
    executable-level drop as an ERROR, not just the summary."""

    class FakeExecutable:
        def as_text(self):
            # zero alias entries kept, though lowering kept the donations
            return "HloModule jit_f, input_output_alias={ }, entry_computation_layout=..."

        def memory_analysis(self):
            raise NotImplementedError

        @property
        def input_shardings(self):
            raise NotImplementedError

    def f(p, b):
        return p * 2 + b.sum(), p + 1.0

    lowered = jax.jit(f, donate_argnums=(0,)).lower(jnp.ones((16, 16)), jnp.ones((4,)))
    report = audit_lowered(lowered, compiled=FakeExecutable(), label="exec_drop")
    assert [f_.code for f_ in report.errors] == ["DONATION_DROPPED"]
    assert "executable aliased only 0" in report.errors[0].message
    assert report.inventory["donation"]["aliased"] == 0


def test_seeded_host_sync_is_caught():
    step = jax.jit(lambda x: x * 2.0)
    step(jnp.ones((8,)))  # warm
    with HazardSanitizer(label="test-window") as sanitizer:
        out = step(jnp.ones((8,)))
        _ = float(out.sum())  # the injected hidden sync
    findings = [f for f in sanitizer.report.findings if f.code == "HOST_SYNC"]
    assert findings, sanitizer.report.render()
    assert findings[0].severity == "error"
    # the call site points at THIS file, not jax internals
    assert "test_analysis.py" in (findings[0].path or "")


def test_seeded_recompile_is_caught_and_explained():
    step = jax.jit(lambda x: x * 3.0)
    step(jnp.ones((8,)))  # warm at bucket A
    with HazardSanitizer(label="test-window") as sanitizer:
        watched = sanitizer.watch(step, label="step")
        watched(jnp.ones((8,)))
        watched(jnp.ones((16,)))  # bucket change: forced retrace
    report = sanitizer.report
    recompiles = [f for f in report.findings if f.code == "WARM_RECOMPILE"]
    assert recompiles, report.render()
    # explain_recompile names the exact leaf and the shape transition
    assert sanitizer.recompile_explanations
    summary = sanitizer.recompile_explanations[0]["summary"]
    assert "(8,)" in summary and "(16,)" in summary


def test_sanitizer_catches_cache_miss_with_key():
    from accelerate_tpu.utils.jit_cache import dot_keyed_jit

    class Owner:
        pass

    owner = Owner()
    dot_keyed_jit(owner, "_cache", ("warm",), lambda: 1)
    with HazardSanitizer(label="window") as sanitizer:
        dot_keyed_jit(owner, "_cache", ("warm",), lambda: 1)  # hit: fine
        dot_keyed_jit(owner, "_cache", ("cold", 512), lambda: 2)  # miss
    misses = [f for f in sanitizer.report.findings if f.code == "CACHE_MISS"]
    assert len(misses) == 1
    assert misses[0].data["misses"] == 1
    assert "cold" in str(misses[0].data["recent_miss_keys"])


# -- program audit units -------------------------------------------------------


def test_fp64_leak_detection():
    from jax.experimental import enable_x64

    with enable_x64():
        lowered = jax.jit(lambda a: a * 2.0).lower(jnp.ones((4,), jnp.float64))
        report = audit_lowered(lowered, compile=False, label="x64", expect_donation=False)
        assert [f.code for f in report.errors] == ["FP64_LEAK"]
        relaxed = audit_lowered(
            lowered, compile=False, label="x64", expect_donation=False, allow_fp64=True
        )
        assert relaxed.errors == []


def test_large_baked_constant_detection():
    table = jnp.asarray(np.random.default_rng(0).normal(size=(512, 1024)), jnp.float32)

    def closes_over(x):
        return x @ table  # 2 MiB constant baked into the program

    lowered = jax.jit(closes_over).lower(jnp.ones((4, 512)))
    report = audit_lowered(lowered, compile=False, label="const", expect_donation=False)
    large = [f for f in report.findings if f.code == "LARGE_CONSTANT"]
    assert large and large[0].data["largest_bytes"] >= 2 * (1 << 20)


def test_replication_audit_severity_follows_intent():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()), ("data",))
    replicated = NamedSharding(mesh, PartitionSpec())
    big = jax.device_put(jnp.ones((512, 1024)), replicated)  # 2 MiB, replicated
    lowered = jax.jit(lambda p: p * 2.0).lower(big)
    compiled = lowered.compile()
    info = audit_lowered(
        lowered, compiled=compiled, label="repl", expect_donation=False, sharded_intent=False
    )
    assert [f.code for f in info.findings] == ["REPLICATED_PARAM_INFO"]
    assert info.errors == []
    hard = audit_lowered(
        lowered, compiled=compiled, label="repl", expect_donation=False, sharded_intent=True
    )
    assert [f.code for f in hard.errors] == ["REPLICATED_PARAM"]


def test_collective_inventory_parses_both_ir_forms():
    hlo = "\n".join(
        [
            "  %ar = f32[1024]{0} all-reduce(f32[1024]{0} %x), replica_groups={}",
            "  %ag = bf16[8,256]{1,0} all-gather(bf16[1,256]{1,0} %y), dimensions={0}",
        ]
    )
    inv = collective_inventory(hlo)
    assert inv["all_reduce"] == {"count": 1, "bytes": 4096}
    assert inv["all_gather"] == {"count": 1, "bytes": 8 * 256 * 2}
    shlo = '%4 = "stablehlo.reduce_scatter"(%3) : (tensor<64xf32>) -> tensor<8xf32>'
    assert collective_inventory(shlo)["reduce_scatter"] == {"count": 1, "bytes": 32}


def test_collective_inventory_quantized_dtypes():
    """The int8 serving path's collectives (s8/u8 in post-SPMD HLO, i8/ui8
    in StableHLO) and sub-byte s4 must size correctly — a parser that only
    knows float classes silently drops them from the inventory, and from
    every contract built on it."""
    hlo = "\n".join(
        [
            "  %ag = s8[8,256]{1,0} all-gather(s8[1,256]{1,0} %q), dimensions={0}",
            "  %ar = u8[1024]{0} all-reduce(u8[1024]{0} %x), replica_groups={}",
            "  %p = s4[4096]{0} all-gather(s4[512]{0} %w), dimensions={0}",
        ]
    )
    inv = collective_inventory(hlo)
    assert inv["all_gather"] == {"count": 2, "bytes": 8 * 256 + 4096 // 2}
    assert inv["all_reduce"] == {"count": 1, "bytes": 1024}
    shlo = '%2 = "stablehlo.all_gather"(%1) : (tensor<1x64xi8>) -> tensor<8x64xi8>'
    assert collective_inventory(shlo)["all_gather"] == {"count": 1, "bytes": 512}


def test_collective_inventory_async_start_forms():
    """The overlap work's async spellings must inventory like their sync
    forms: every `-start(` opcode counts once (the done is a different
    opcode), sized from the tuple RESULT — first-type sizing would price an
    all-gather at its (smaller) operand shape, and reduce-scatter/all-to-all
    starts used to vanish entirely."""
    hlo = "\n".join(
        [
            "  %ag = (f32[1024]{0}, f32[8192]{0}) all-gather-start(f32[1024]{0} %p), dimensions={0}",
            "  %agd = f32[8192]{0} all-gather-done(f32[8192]{0} %ag)",
            "  %rs = (f32[8192]{0}, f32[1024]{0}) reduce-scatter-start(f32[8192]{0} %q), dimensions={0}",
            "  %rsd = f32[1024]{0} reduce-scatter-done(f32[1024]{0} %rs)",
            "  %aa = (f32[2048]{0}, f32[2048]{0}) all-to-all-start(f32[2048]{0} %r)",
        ]
    )
    inv = collective_inventory(hlo)
    assert inv["all_gather"] == {"count": 1, "bytes": 8192 * 4}
    # reduce-scatter's tuple is (operand, result): max = the 8192 operand —
    # a deliberate over- not under-estimate; the schedule pass prices the
    # matched done exactly
    assert inv["reduce_scatter"] == {"count": 1, "bytes": 8192 * 4}
    assert inv["all_to_all"] == {"count": 1, "bytes": 2048 * 4}


def test_large_baked_constant_quantized_dtypes():
    """A >=1MiB int8 table baked into a program (the int8 serving path's
    dequant scales/tables) must trip LARGE_CONSTANT like a float one."""
    from accelerate_tpu.analysis import constant_audit

    hlo = "  %c = s8[2097152]{0} constant({...})"
    findings = constant_audit(hlo, label="int8_const")
    assert [f.code for f in findings] == ["LARGE_CONSTANT"]
    assert findings[0].data["largest_bytes"] == 2 << 20
    shlo = "  %c = stablehlo.constant dense_resource<w> : tensor<1048576x2xi8>"
    findings = constant_audit(shlo, label="int8_const")
    assert [f.code for f in findings] == ["LARGE_CONSTANT"]
    assert findings[0].data["largest_bytes"] == 2 << 20
    # sub-byte packing: 4M s4 elements are 2 MiB, not 4
    sub = "  %c = s4[4194304]{0} constant({...})"
    findings = constant_audit(sub, label="int4_const")
    assert findings and findings[0].data["largest_bytes"] == 2 << 20


def test_schedule_pass_classifies_overlap():
    """Async pair with independent compute between start and done =
    overlapped; async pair whose done is right behind the start (or a plain
    sync collective) = serialized, its bytes on the critical path."""
    from accelerate_tpu.analysis import collective_schedule

    hlo = "\n".join(
        [
            "ENTRY %main {",
            "  %p = f32[1024]{0} parameter(0)",
            "  %q = f32[1024]{0} parameter(1)",
            "  %ag = f32[8192]{0} all-gather-start(f32[1024]{0} %p), dimensions={0}",
            "  %ind = f32[1024]{0} multiply(f32[1024]{0} %q, f32[1024]{0} %q)",
            "  %agd = f32[8192]{0} all-gather-done(f32[8192]{0} %ag)",
            "  %ar = f32[1024]{0} all-reduce-start(f32[1024]{0} %ind), to_apply=%add",
            "  %ard = f32[1024]{0} all-reduce-done(f32[1024]{0} %ar)",
            "  %sync = f32[512]{0} all-reduce(f32[512]{0} %q), to_apply=%add",
            "}",
        ]
    )
    s = collective_schedule(hlo)
    assert s["total_count"] == 3 and s["async_count"] == 2
    assert s["overlapped_count"] == 1  # the all-gather hid behind %ind
    assert s["serialized_count"] == 2  # back-to-back all-reduce + the sync op
    assert s["overlapped_comm_bytes"] == 8192 * 4
    assert s["serialized_comm_bytes"] == 1024 * 4 + 512 * 4
    per = s["per_kind"]
    assert per["all_gather"]["overlapped_count"] == 1
    assert per["all_reduce"]["serialized_bytes"] == 1024 * 4 + 512 * 4


def test_schedule_pass_dependent_compute_is_not_overlap():
    """Compute that CONSUMES the start's value (directly or transitively)
    hides no latency — it must not count as overlap; nor do data-movement
    ops like copy/reshape sitting between start and done."""
    from accelerate_tpu.analysis import collective_schedule

    hlo = "\n".join(
        [
            "ENTRY %main {",
            "  %p = f32[1024]{0} parameter(0)",
            "  %ag = f32[8192]{0} all-gather-start(f32[1024]{0} %p), dimensions={0}",
            "  %use = f32[8192]{0} multiply(f32[8192]{0} %ag, f32[8192]{0} %ag)",
            "  %chain = f32[8192]{0} add(f32[8192]{0} %use, f32[8192]{0} %use)",
            "  %mv = f32[8192]{0} copy(f32[8192]{0} %p)",
            "  %agd = f32[8192]{0} all-gather-done(f32[8192]{0} %ag)",
            "}",
        ]
    )
    s = collective_schedule(hlo)
    assert s["overlapped_count"] == 0 and s["serialized_count"] == 1


def test_schedule_pass_unmatched_done_is_serialized():
    """An async start whose done the pass cannot pair (async-wrapped in a
    different computation) must classify conservatively as SERIALIZED — the
    walk saw the rest of the computation, not the start→done window, so
    crediting 'overlap' would silently shrink the serialized-comm baseline."""
    from accelerate_tpu.analysis import collective_schedule

    hlo = "\n".join(
        [
            "ENTRY %main {",
            "  %p = f32[1024]{0} parameter(0)",
            "  %q = f32[1024]{0} parameter(1)",
            "  %ag = f32[8192]{0} all-gather-start(f32[1024]{0} %p), dimensions={0}",
            "  %ind = f32[1024]{0} multiply(f32[1024]{0} %q, f32[1024]{0} %q)",
            "}",
        ]
    )
    s = collective_schedule(hlo)
    assert s["total_count"] == 1
    assert s["overlapped_count"] == 0 and s["serialized_count"] == 1
    assert s["serialized_comm_bytes"] == 8192 * 4  # sized from the start

    # real XLA starts are tuple-typed (operand, result): the size must come
    # from the LARGEST type in the result tuple, not the first (the input)
    tup = "\n".join(
        [
            "ENTRY %main {",
            "  %p = f32[1024]{0} parameter(0)",
            "  %ag = (f32[1024]{0}, f32[8192]{0}) all-gather-start(f32[1024]{0} %p), dimensions={0}",
            "}",
        ]
    )
    s = collective_schedule(tup)
    assert s["serialized_count"] == 1
    assert s["serialized_comm_bytes"] == 8192 * 4


def test_explain_recompile_names_the_leaf():
    a = signature_of(({"ids": jnp.ones((4, 8), jnp.int32), "n": 3},))
    b = signature_of(({"ids": jnp.ones((4, 12), jnp.int32), "n": 3},))
    diff = explain_recompile(a, b)
    assert list(diff["changed"]) == ["0/ids"]
    assert "(4, 8)" in diff["summary"] and "(4, 12)" in diff["summary"]
    same = explain_recompile(a, a)
    assert "identical" in same["summary"]
    static = explain_recompile(
        signature_of(({"n": 3},)), signature_of(({"n": 4},))
    )
    assert "static:3" in str(static["changed"])


def test_explain_recompile_names_weak_type_flip():
    """A Python-scalar-born array (weak dtype) and an explicit one share
    shape AND dtype but are different trace keys — the signature must carry
    the weak-type bit so the diff names the culprit leaf instead of
    reporting "identical abstract signatures"."""
    weak = jnp.asarray(1.0)  # Python float: weak f32
    strong = jnp.float32(1.0) * jnp.ones(())  # committed f32
    assert weak.aval.weak_type and not strong.aval.weak_type
    a = signature_of(({"lr": weak},))
    b = signature_of(({"lr": strong},))
    assert a["0/lr"].endswith("/weak") and not b["0/lr"].endswith("/weak")
    diff = explain_recompile(a, b)
    assert list(diff["changed"]) == ["0/lr"]
    assert "weak" in diff["summary"]
    assert "identical" not in diff["summary"]


def test_donation_drop_warning_branches():
    assert donation_drop_warning(0, 0, "tpu") is None
    assert donation_drop_warning(4, 4, "tpu") is None
    dropped = donation_drop_warning(4, 1, "tpu")
    assert dropped["event"] == "donation_dropped"
    assert "1/4" in dropped["message"]


# -- eager-path donation (optimizer.py) ---------------------------------------


def test_optimizer_verify_donation():
    class Linear:
        def init(self, rng):
            return {"w": jnp.ones((32, 32)), "b": jnp.zeros((32,))}

        def apply(self, params, x):
            return x @ params["w"] + params["b"]

    accelerator = Accelerator()
    model = accelerator.prepare_model(Linear())
    optimizer = accelerator.prepare_optimizer(optax.adam(1e-3))
    report = optimizer.verify_donation()
    assert report.errors == [], report.render()
    donation = report.inventory["donation"]
    assert donation["declared"] > 0
    assert donation["aliased"] == donation["declared"]


# -- serving donation consult (engine satellite) ------------------------------


class _TelemetryStub:
    """Just enough hub for the engine: a compile tracker + record capture."""

    def __init__(self):
        from accelerate_tpu.telemetry import CompileTracker

        self.compiles = CompileTracker().start()
        self.records = []

    def write_record(self, kind, payload):
        self.records.append({"kind": kind, **payload})
        return self.records[-1]


def test_engine_consults_donation_after_first_compile():
    model = Llama("llama-tiny")
    telemetry = _TelemetryStub()
    engine = ServingEngine(
        model, model.init(jax.random.key(0)), num_slots=2, max_len=32, telemetry=telemetry
    )
    engine._donate = False  # CPU default: consult is a no-op
    engine.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=2)
    engine.run()
    assert engine._donation_checked
    assert not [r for r in telemetry.records if r["kind"] == "analysis"]

    # donation requested (the TPU/GPU path, verifiable on CPU too): the
    # engine must consult the audit once and record the verdict
    engine2 = ServingEngine(
        model, model.init(jax.random.key(1)), num_slots=2, max_len=32, telemetry=telemetry
    )
    engine2._donate = True
    engine2.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=2)
    engine2.run()
    verdicts = [r for r in telemetry.records if r["kind"] == "analysis"]
    assert verdicts and verdicts[0]["event"] == "donation_verified"
    assert verdicts[0]["declared"] == verdicts[0]["aliased"] > 0


# -- telemetry: steady-state recompile record with signature diff -------------


def test_compile_record_carries_signature_diff(tmp_path):
    accelerator = Accelerator(
        telemetry_config=TelemetryConfig(dir=str(tmp_path), sample_every=2)
    )
    model = Bert("bert-tiny")
    accelerator.prepare_model(model)
    accelerator.prepare_optimizer(optax.adamw(1e-4))
    step = accelerator.compiled_step(Bert.loss_fn(model))
    telemetry = accelerator.telemetry
    batch_a = _bert_batch(model, seq_len=16)
    for _ in range(3):
        telemetry.step(step(batch_a))
    batch_b = _bert_batch(model, seq_len=24)  # steady-state shape change
    telemetry.step(step(batch_b))
    telemetry.finish()
    records = [
        json.loads(line) for line in open(tmp_path / "telemetry.jsonl", encoding="utf-8")
    ]
    compiles = [r for r in records if r["kind"] == "compile"]
    assert compiles, [r["kind"] for r in records]
    explain = compiles[-1]["explain"]
    changed = " ".join(explain["changed"])
    assert "input_ids" in changed
    assert "(8, 16)" in explain["summary"] and "(8, 24)" in explain["summary"]


# -- source lint ---------------------------------------------------------------

_HAZARD_SOURCE = '''
import time, random
import numpy as np
import jax

@jax.jit
def step(params, batch):
    t = time.time()
    r = random.random()
    u = np.random.uniform()
    v = batch.sum().item()
    w = np.asarray(batch)
    if params > 0:
        pass
    while batch:
        break
    print(w)
    results.append(w)
    global counter
    return params

def loss(params, batch):
    return float(batch)

grad = jax.value_and_grad(loss)
'''


def test_lint_catches_every_hazard_class():
    findings = lint_source(_HAZARD_SOURCE, "hazards.py")
    codes = {f.code for f in findings}
    assert {
        "HOST_TIME", "HOST_RANDOM", "LINT_HOST_SYNC", "TRACED_BRANCH",
        "TRACE_PRINT", "CAPTURED_MUTATION_CALL", "CAPTURED_MUTATION", "HOST_CAST",
    } <= codes
    # both the decorated fn and the one passed to value_and_grad are scoped
    assert any("hazards.py:23" in (f.path or "") for f in findings)


def test_lint_jax_random_is_not_host_random():
    source = '''
import jax
from jax import random

@jax.jit
def step(params, key):
    noise = random.normal(key, params.shape)   # the keyed idiom IS the fix
    return params + noise
'''
    assert lint_source(source, "keyed.py") == []
    aliased = source.replace("from jax import random", "from jax import random as jrandom").replace(
        "random.normal", "jrandom.normal"
    )
    assert lint_source(aliased, "keyed2.py") == []
    # numpy's random module stays flagged
    source_np = source.replace("from jax import random", "from numpy import random")
    assert [f.code for f in lint_source(source_np, "np.py")] == ["HOST_RANDOM"]


def test_lint_parse_error_has_its_own_code():
    findings = lint_source("def broken(:\n", "bad.py")
    assert [f.code for f in findings] == ["PARSE_ERROR"]
    assert findings[0].severity == "warning"
    assert "could not parse" in findings[0].message


def test_sanitizer_records_h2d_guard_trip():
    step = jax.jit(lambda x: x + 1.0)
    step(jnp.ones((4,)))  # warm (device-committed input)
    with pytest.raises(Exception, match="host-to-device"):
        with HazardSanitizer(label="h2d", transfer_guard="disallow") as sanitizer:
            step(np.ones((4,), np.float32))  # implicit per-call H2D upload
    trips = [f for f in sanitizer.report.findings if f.code == "H2D_TRANSFER"]
    assert trips and "test_analysis.py" in trips[0].path


def test_lint_safe_patterns_not_flagged():
    source = '''
import jax
import jax.numpy as jnp

@jax.jit
def step(params, batch, mask):
    if mask is None:                 # static structure check
        mask = jnp.ones_like(batch)
    if batch.ndim > 2:               # shapes are trace-time constants
        batch = batch.reshape(batch.shape[0], -1)
    acc = []
    acc.append(batch)                # locally bound: not captured state
    updates, state = tx.update(batch, params)   # consumed result: functional
    return updates

def helper(x):
    import time
    return time.time()               # NOT traced: no finding
'''
    assert lint_source(source, "clean.py") == []


def test_lint_pragma_waivers():
    source = '''
import time
import jax

@jax.jit
def line_waived(params):
    return time.time()  # accel-lint: disable=HOST_TIME

@jax.jit
def fn_waived(params):  # accel-lint: disable=all
    t = time.time()
    return params.sum().item()

@jax.jit
def not_waived(params):
    return time.time()
'''
    findings = lint_source(source, "waived.py")
    assert len(findings) == 1
    assert "waived.py:16" in findings[0].path


def test_lint_detects_all_traced_entry_forms():
    source = '''
import jax
from functools import partial
import time

@partial(jax.jit, static_argnums=(1,))
def decorated(x, n):
    return time.time()

def by_call(x):
    return time.time()

jitted = jax.jit(by_call)

def scanned(carry, x):
    return carry, time.time()

jax.lax.scan(scanned, 0, None)

factory = jax.jit(donate_argnums=(0,))(lambda x: time.time())
'''
    findings = lint_source(source, "forms.py")
    assert len([f for f in findings if f.code == "HOST_TIME"]) == 4


def test_repo_lint_gate_zero_unwaived_findings():
    """Satellite gate: the repo's own code and examples stay lint-clean —
    any new finding must be fixed or explicitly waived with a pragma. Every
    waiver must NAME its code (no blanket ``disable=all``), and — enforced
    by the LINT_WAIVER_UNUSED audit inside lint_paths itself — every waiver
    must still be suppressing something."""
    from accelerate_tpu.analysis.lint import PRAGMA_RE, iter_python_files

    lint_targets = [
        os.path.join(REPO_ROOT, "accelerate_tpu"),
        os.path.join(REPO_ROOT, "examples"),
        os.path.join(REPO_ROOT, "bench.py"),
    ]
    report = lint_paths(lint_targets)
    assert report.findings == [], report.render()
    assert report.inventory["files_scanned"] > 50

    for path in iter_python_files(lint_targets):
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                m = PRAGMA_RE.search(line)
                if m:
                    codes = {c.strip().upper() for c in m.group(1).split(",")}
                    assert "ALL" not in codes, f"{path}:{lineno} blanket waiver"


# -- findings / report / catalog ----------------------------------------------


def test_finding_defaults_from_catalog():
    finding = Finding("HOST_SYNC", "msg")
    assert finding.severity == "error"
    assert finding.fix_hint
    report = AnalysisReport(findings=[finding, Finding("CACHE_MISS", "m2")])
    assert report.has_errors and len(report.warnings) == 1
    assert report.counts()["error"] == 1
    assert report.to_dict()["findings"][0]["code"] == "HOST_SYNC"  # severity-sorted


def test_docs_catalog_in_sync():
    """docs/analysis.md documents every finding ID (single source: CATALOG)."""
    doc = open(os.path.join(REPO_ROOT, "docs", "analysis.md"), encoding="utf-8").read()
    for code in CATALOG:
        assert code in doc, f"finding {code} missing from docs/analysis.md"


# -- CLI ----------------------------------------------------------------------


def test_cli_analyze_lint_exit_codes(tmp_path, capsys):
    from accelerate_tpu.commands.cli import main

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax, time\n@jax.jit\ndef f(x):\n    return time.time()\n"
    )
    assert main(["analyze", str(bad)]) == 1
    out = capsys.readouterr().out
    assert "HOST_TIME" in out
    good = tmp_path / "good.py"
    good.write_text("import jax\n@jax.jit\ndef f(x):\n    return x * 2\n")
    assert main(["analyze", str(good)]) == 0
    capsys.readouterr()
    assert main(["analyze", str(good), "--json"]) == 0
    assert json.loads(capsys.readouterr().out)[0]["counts"]["error"] == 0
