"""Tracker tests (reference tests/test_tracking.py, 531 LoC): real
TensorBoard event files, JSONL round trip, resolution logic, Accelerator.log
fan-out via a mock tracker."""

import glob
import json
import os

import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.tracking import (
    GeneralTracker,
    JSONLTracker,
    TensorBoardTracker,
    filter_trackers,
)
from accelerate_tpu.utils import ProjectConfiguration


class MockTracker(GeneralTracker):
    name = "mock"
    requires_logging_directory = False

    def __init__(self):
        self.config = None
        self.logged = []
        self.finished = False

    def store_init_configuration(self, values):
        self.config = values

    def log(self, values, step=None, **kwargs):
        self.logged.append((values, step))

    def finish(self):
        self.finished = True


def test_jsonl_tracker_roundtrip(tmp_path):
    tracker = JSONLTracker("run1", logging_dir=str(tmp_path))
    tracker.store_init_configuration({"lr": 0.1})
    tracker.log({"loss": 1.5}, step=0)
    tracker.log({"loss": 0.5, "acc": 0.9}, step=1)
    tracker.finish()
    lines = [json.loads(l) for l in open(tmp_path / "run1" / "metrics.jsonl")]
    assert lines[0]["_config"] == {"lr": 0.1}
    assert lines[1] == {"loss": 1.5, "_step": 0, "_time": lines[1]["_time"]}
    assert lines[2]["acc"] == 0.9


def test_tensorboard_tracker_writes_event_files(tmp_path):
    tracker = TensorBoardTracker("run1", logging_dir=str(tmp_path))
    tracker.store_init_configuration({"lr": 0.1, "epochs": 2})
    tracker.log({"loss": 1.0, "note": "hello", "grouped": {"a": 1.0, "b": 2.0}}, step=0)
    tracker.finish()
    assert glob.glob(str(tmp_path / "run1" / "events.out.tfevents.*"))
    hparams = json.load(open(tmp_path / "run1" / "hparams.json"))
    assert hparams == {"lr": 0.1, "epochs": 2}


def test_filter_trackers_resolution(tmp_path):
    # "all" resolves to every available tracker (jsonl always available)
    trackers = filter_trackers("all", str(tmp_path), "proj", config={"x": 1})
    names = {t.name for t in trackers}
    assert "jsonl" in names and "tensorboard" in names
    assert "comet_ml" not in names and "aim" not in names  # not installed → skipped
    # config was stored on every resolved tracker
    assert json.loads(open(tmp_path / "proj" / "metrics.jsonl").readline())["_config"] == {"x": 1}


def test_filter_trackers_unknown_raises():
    with pytest.raises(ValueError, match="Unknown tracker"):
        filter_trackers("not-a-tracker", None, "proj")


def test_filter_trackers_instance_passthrough():
    mock = MockTracker()
    trackers = filter_trackers([mock], None, "proj", config={"seed": 1})
    assert trackers == [mock]
    assert mock.config == {"seed": 1}


def test_filter_trackers_requested_but_missing_skips(caplog):
    # comet_ml is not installed in this image: requested explicitly → warn+skip
    trackers = filter_trackers(["comet_ml", "jsonl"], "/tmp", "proj")
    assert [t.name for t in trackers] == ["jsonl"]


def test_accelerator_log_fans_out(tmp_path):
    mock = MockTracker()
    acc = Accelerator(
        log_with=[mock],
        project_config=ProjectConfiguration(project_dir=str(tmp_path), logging_dir=str(tmp_path)),
    )
    acc.init_trackers("proj", {"lr": 3e-4})
    assert mock.config == {"lr": 3e-4}
    acc.log({"loss": 0.1}, step=5)
    acc.log({"loss": 0.05}, step=6)
    assert mock.logged == [({"loss": 0.1}, 5), ({"loss": 0.05}, 6)]
    acc.end_training()
    assert mock.finished


def test_log_images_fallback_warns_not_crashes():
    mock = MockTracker()
    mock.log_images({"img": None})  # base-class fallback: warn once, no-op


def test_trackers_registered():
    from accelerate_tpu.tracking import _available_trackers

    for name in ("tensorboard", "wandb", "mlflow", "comet_ml", "aim", "clearml", "dvclive", "jsonl"):
        assert name in _available_trackers
