"""HF/torch checkpoint interop: import (transpose + restack), export (inverse),
tied embeddings, and the load_checkpoint_and_dispatch route
(reference utils/modeling.py:1541, 606-693)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import Llama
from accelerate_tpu.models.config import get_config
from accelerate_tpu.utils.hf_import import (
    export_hf_llama,
    import_hf_llama,
    load_checkpoint_in_model,
    load_hf_state_dict,
    looks_like_hf_checkpoint,
)


def _model(tie=False):
    cfg = dataclasses.replace(get_config("llama-tiny"), tie_embeddings=tie)
    return Llama(cfg)


def _params(model, seed=0):
    return jax.device_get(model.init(jax.random.key(seed)))


def _save_hf(flat, directory):
    from safetensors.numpy import save_file

    save_file({k: np.ascontiguousarray(v) for k, v in flat.items()},
              str(directory / "model.safetensors"))


def test_export_import_roundtrip_exact():
    """our tree → HF naming → back: bitwise equal (covers every transpose)."""
    model = _model()
    params = _params(model)
    flat = export_hf_llama(params, model.config)
    assert looks_like_hf_checkpoint(flat)
    # HF naming and torch [out, in] orientation
    cfg = model.config
    assert flat["model.layers.0.self_attn.q_proj.weight"].shape == (
        cfg.num_heads * cfg.dim_per_head,
        cfg.hidden_size,
    )
    back = import_hf_llama(flat, model.config)
    for key in ("embed_tokens", "final_norm", "lm_head"):
        np.testing.assert_array_equal(back[key], np.asarray(params[key]))
    for key, value in params["layers"].items():
        np.testing.assert_array_equal(back["layers"][key], np.asarray(value))


def test_import_forward_parity(tmp_path):
    """Logits from an HF-layout checkpoint on disk match the source params."""
    model = _model()
    params = _params(model)
    _save_hf(export_hf_llama(params, model.config), tmp_path)
    imported = load_checkpoint_in_model(model, str(tmp_path))
    tokens = jnp.asarray([[1, 5, 9, 2]], jnp.int32)
    expected = model.apply(params, tokens)
    got = model.apply(jax.tree.map(jnp.asarray, imported), tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-6)


def test_tied_embedding_copy_is_dropped():
    """torch ties by pointer; serialized that's an equal copy — drop it."""
    model = _model(tie=True)
    params = _params(model)
    assert "lm_head" not in params
    flat = export_hf_llama(params, model.config)
    flat["lm_head.weight"] = np.asarray(params["embed_tokens"])  # [v, h], tied copy
    back = import_hf_llama(flat, model.config)
    assert "lm_head" not in back


def test_tied_config_with_distinct_lm_head_raises():
    model = _model(tie=True)
    params = _params(model)
    flat = export_hf_llama(params, model.config)
    flat["lm_head.weight"] = np.random.default_rng(0).normal(
        size=(model.config.vocab_size, model.config.hidden_size)
    ).astype(np.float32)
    with pytest.raises(ValueError, match="distinct lm_head"):
        import_hf_llama(flat, model.config)


def test_untied_config_missing_lm_head_raises():
    model = _model(tie=False)
    params = _params(model)
    flat = export_hf_llama(params, model.config)
    del flat["lm_head.weight"]
    with pytest.raises(KeyError, match="tie_embeddings"):
        import_hf_llama(flat, model.config)


def test_wrong_config_shape_mismatch_raises():
    model = _model()
    params = _params(model)
    flat = export_hf_llama(params, model.config)
    small = dataclasses.replace(model.config, intermediate_size=model.config.intermediate_size * 2)
    with pytest.raises(ValueError, match="shape"):
        import_hf_llama(flat, small)


def test_load_checkpoint_in_model_native_layout(tmp_path):
    """Native flat layout still loads (numpy leaves, no device allocation)."""
    from accelerate_tpu.checkpointing import save_model_weights

    model = _model()
    params = _params(model)
    save_model_weights(params, str(tmp_path))
    loaded = load_checkpoint_in_model(model, str(tmp_path))
    leaves = jax.tree.leaves(loaded)
    assert all(isinstance(l, np.ndarray) for l in leaves)
    np.testing.assert_array_equal(loaded["embed_tokens"], np.asarray(params["embed_tokens"]))


def test_load_checkpoint_and_dispatch_hf_layout(tmp_path):
    """The big-model entry point accepts an HF-layout directory end to end."""
    from accelerate_tpu import load_checkpoint_and_dispatch

    model = _model()
    params = _params(model)
    _save_hf(export_hf_llama(params, model.config), tmp_path)
    lm = load_checkpoint_and_dispatch(model, str(tmp_path), device_map="auto", dtype=jnp.float32)
    tokens = jnp.asarray([[1, 5, 9, 2]], jnp.int32)
    expected = model.apply(params, tokens)
    got = lm(tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=2e-5, atol=1e-5)
