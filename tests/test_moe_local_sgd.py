"""Expert parallelism (MoEBlock over the expert mesh axis) and LocalSGD
(reference local_sgd.py:19-102; DeepSpeed MoE plumbing accelerator.py:1594)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from accelerate_tpu import Accelerator, LocalSGD, ParallelismConfig
from accelerate_tpu.models import MoEBlock
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _x(b=4, s=8, h=32, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(size=(b, s, h)).astype(np.float32))


# -- MoE ---------------------------------------------------------------------


def test_moe_expert_axis_shards_weights_and_matches_expert1():
    """The expert axis must change the layout but never the math."""
    block = MoEBlock(hidden_size=32, intermediate_size=64, num_experts=4, top_k=2)
    params_host = jax.device_get(block.init(jax.random.key(0)))
    x = _x()
    outs = {}
    for expert in (1, 2):
        _reset()
        acc = Accelerator(parallelism=ParallelismConfig(expert=expert))
        prepared = acc.prepare_model(MoEBlock(32, 64, 4, top_k=2), params=jax.tree.map(jnp.asarray, params_host))
        if expert > 1:
            assert prepared.params_shardings["w_up"].spec == P("expert", None, None)
        y = jax.jit(prepared.module.apply)(prepared.params, x)
        outs[expert] = np.asarray(jax.device_get(y))
    np.testing.assert_allclose(outs[1], outs[2], rtol=2e-5, atol=1e-5)


def test_moe_routes_to_multiple_experts():
    """With enough capacity every token's top-k outputs combine to ~1 gates."""
    block = MoEBlock(hidden_size=16, intermediate_size=32, num_experts=4, top_k=2, capacity_factor=4.0)
    params = block.init(jax.random.key(1))
    x = _x(2, 4, 16, seed=1)
    y, aux = block.apply(params, x, return_aux=True)
    assert y.shape == x.shape
    assert np.isfinite(float(aux)) and float(aux) > 0
    # balanced-ish router at init: aux loss near its minimum value (weight * 1)
    assert float(aux) < block.aux_loss_weight * block.num_experts


def test_moe_capacity_drops_overflow_tokens():
    """Tokens over expert capacity contribute zero (Switch semantics)."""
    block = MoEBlock(hidden_size=8, intermediate_size=16, num_experts=2, top_k=1, capacity_factor=0.51)
    params = block.init(jax.random.key(2))
    # zero router → all logits tie → top_k picks expert 0 for every token
    params = dict(params)
    params["router"] = jnp.zeros_like(params["router"])
    x = _x(1, 8, 8, seed=2)
    y = block.apply(params, x)
    # capacity = ceil(1*8/2*0.51) = 3 slots on expert 0 → 5 of 8 tokens dropped
    per_token = np.abs(np.asarray(y[0])).sum(-1)
    assert (per_token > 1e-6).sum() == block.capacity(8)


def test_moe_trains_under_accelerator():
    _reset()
    acc = Accelerator(parallelism=ParallelismConfig(expert=2))
    block = MoEBlock(16, 32, num_experts=4, top_k=2, capacity_factor=2.0)
    model = acc.prepare_model(block)
    opt = acc.prepare_optimizer(optax.adam(1e-2))
    x = _x(4, 8, 16, seed=3)
    target = jnp.tanh(x[..., ::-1])

    def loss_fn(params, batch):
        y, aux = block.apply(params, batch["x"], return_aux=True)
        return jnp.mean((y - batch["y"]) ** 2) + aux

    losses = []
    for _ in range(12):
        losses.append(float(acc.backward(loss_fn, {"x": x, "y": target})))
        opt.step()
        opt.zero_grad()
    assert losses[-1] < losses[0] * 0.7


def test_moe_topk_validation():
    with pytest.raises(ValueError, match="top_k"):
        MoEBlock(8, 16, num_experts=2, top_k=3)


# -- LocalSGD ----------------------------------------------------------------


class LinearModel:
    def init(self, rng):
        del rng
        return {"a": jnp.zeros(()), "b": jnp.zeros(())}

    @staticmethod
    def apply(params, x):
        return params["a"] * x + params["b"]


def _loss(params, batch):
    return jnp.mean((LinearModel.apply(params, batch["x"]) - batch["y"]) ** 2)


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n,)).astype(np.float32)
    return {"x": jnp.asarray(x), "y": jnp.asarray(2 * x + 3 + 0.01 * rng.normal(size=(n,)).astype(np.float32))}


def test_local_sgd_converges():
    _reset()
    acc = Accelerator()
    model = acc.prepare_model(LinearModel())
    batch = _data()
    with LocalSGD(acc, model, optax.sgd(0.1), local_sgd_steps=4) as lsgd:
        losses = [float(lsgd.step(_loss, batch)) for _ in range(24)]
    assert losses[-1] < losses[0] * 0.05
    final = jax.device_get(model.params)
    assert abs(float(final["a"]) - 2.0) < 0.3
    assert abs(float(final["b"]) - 3.0) < 0.3


def test_local_sgd_k1_matches_synchronous():
    """local_sgd_steps=1 (sync every step) must equal plain synchronized SGD
    on the full batch — averaging replicas each step == averaging gradients
    for SGD (linear update rule)."""
    _reset()
    acc = Accelerator()
    model = acc.prepare_model(LinearModel())
    batch = _data()
    with LocalSGD(acc, model, optax.sgd(0.1), local_sgd_steps=1) as lsgd:
        for _ in range(6):
            lsgd.step(_loss, batch)
    local = jax.device_get(model.params)

    # reference: plain full-batch SGD (grad of mean == mean of per-shard grads)
    params = {"a": jnp.zeros(()), "b": jnp.zeros(())}
    tx = optax.sgd(0.1)
    opt_state = tx.init(params)
    for _ in range(6):
        g = jax.grad(_loss)(params, batch)
        updates, opt_state = tx.update(g, opt_state, params)
        params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(float(local["a"]), float(params["a"]), rtol=1e-5)
    np.testing.assert_allclose(float(local["b"]), float(params["b"]), rtol=1e-5)


def test_local_sgd_replicas_diverge_between_syncs():
    _reset()
    acc = Accelerator()
    model = acc.prepare_model(LinearModel())
    batch = _data()
    with LocalSGD(acc, model, optax.sgd(0.1), local_sgd_steps=100) as lsgd:
        lsgd.step(_loss, batch)
        replicas = np.asarray(jax.device_get(lsgd.params["a"]))
        # different batch shards → different local params
        assert len(np.unique(np.round(replicas, 6))) > 1


def test_local_sgd_requires_context():
    _reset()
    acc = Accelerator()
    model = acc.prepare_model(LinearModel())
    lsgd = LocalSGD(acc, model, optax.sgd(0.1))
    with pytest.raises(RuntimeError, match="context"):
        lsgd.step(_loss, _data())


def test_local_sgd_disabled_is_synchronous():
    """enabled=False runs the same loop fully synchronized (reference parity)
    — exactly, for ANY optimizer (Adam moments included), since the disabled
    path skips the worker axis entirely."""
    _reset()
    acc = Accelerator()
    model = acc.prepare_model(LinearModel())
    batch = _data()
    with LocalSGD(acc, model, optax.adam(0.1), local_sgd_steps=8, enabled=False) as lsgd:
        for _ in range(6):
            lsgd.step(_loss, batch)
    local = jax.device_get(model.params)

    params = {"a": jnp.zeros(()), "b": jnp.zeros(())}
    tx = optax.adam(0.1)
    opt_state = tx.init(params)
    for _ in range(6):
        g = jax.grad(_loss)(params, batch)
        updates, opt_state = tx.update(g, opt_state, params)
        params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(float(local["a"]), float(params["a"]), rtol=1e-5)
    np.testing.assert_allclose(float(local["b"]), float(params["b"]), rtol=1e-5)


# -- MoE inside the llama family ---------------------------------------------


def test_llama_moe_trains_and_shards_experts():
    """config.num_experts > 1 swaps the MLP for routed experts; the model
    trains under the Accelerator with experts on the expert axis."""
    import optax

    from accelerate_tpu.models import Llama
    from accelerate_tpu.models.config import param_count

    _reset()
    acc = Accelerator(parallelism=ParallelismConfig(expert=2, data=4))
    model = Llama("llama-moe-tiny")
    prepared = acc.prepare(model)
    assert "router" in prepared.params["layers"]
    spec = prepared.params_shardings["layers"]["moe_up"].spec
    assert spec[1] == "expert"
    # exact param count accounting includes the experts
    total = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(prepared.params))
    assert total == param_count(model.config)

    opt = acc.prepare_optimizer(optax.adam(1e-3))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (8, 16)), jnp.int32)
    loss_fn = Llama.loss_fn(model)
    losses = []
    for _ in range(8):
        losses.append(float(acc.backward(loss_fn, {"input_ids": ids})))
        opt.step()
        opt.zero_grad()
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_llama_moe_generate():
    from accelerate_tpu.models import Llama
    from accelerate_tpu.models.generation import generate

    _reset()
    model = Llama("llama-moe-tiny")
    params = model.init(jax.random.key(0))
    out = generate(model, params, jnp.asarray([[1, 2, 3]], jnp.int32), max_new_tokens=4)
    assert out.shape == (1, 7)


def test_llama_moe_loss_includes_balance_term():
    from accelerate_tpu.models import Llama

    _reset()
    model = Llama("llama-moe-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 1024, (2, 16)), jnp.int32)
    logits, aux = model.apply(params, ids, return_aux=True)
    assert float(aux) > 0
    total = float(Llama.loss_fn(model)(params, {"input_ids": ids}))
    # the training loss is CE + aux, not bare CE
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    ce = float(-jnp.take_along_axis(logp, ids[:, 1:][..., None], axis=-1).mean())
    np.testing.assert_allclose(total, ce + float(aux), rtol=1e-5)
