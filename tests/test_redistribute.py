"""The redistribution primitive (parallel/redistribute.py; ISSUE 16).

The claims this file pins, each as a measured property rather than prose:

- **Round-trip bit-equality** (property-style) — random leaf shapes/dtypes
  redistributed across random mesh-pair factorings (reshard, permute,
  shrink-shaped half-mesh pairs, disjoint-device pairs) come back bit-exact
  against both the source values and the host-relay reference, on every
  rung; a plan whose leaves exceed ``max_scratch_bytes`` chunks them so no
  stage stages more than the bound.
- **The plan decides before a byte moves** — rung selection (staged for a
  pure relayout, host-relay for lost devices or a buddy merge) and the
  coverage verdict are metadata-only, and the collective kinds
  (``collective_permute`` / ``all_to_all`` / ``device_put``) match the
  sharding geometry.
- **Transaction + chaos ladder** — a chaos-killed stage
  (``redistribute_fail_at/_stage``, ``ACCELERATE_CHAOS_REDISTRIBUTE_*``)
  never corrupts the source: the ladder degrades staged → host relay with a
  bit-exact result and a ``fell_back`` telemetry outcome, or fails loud
  NAMING the stage when the fallback is pinned off.
- **Epoch-fenced commit** — a transfer planned under epoch N whose store
  moves to N+1 mid-flight is refused AT COMMIT (``StaleEpochError``),
  recorded ``stale_epoch_write_rejected``, source intact.
- **The handoff wire** — ``paged_transfer`` fires the probe (the router's
  chaos window) mid-transfer and a killed page-read stage raises before any
  block is returned.
- **The CAS store** — ``DictStore``'s ``fenced_write``/``mint_epoch`` are a
  real compare-and-swap (threaded mint race: exactly one winner), behavior-
  matched against ``FilesystemStore``'s read-check-write.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from accelerate_tpu.parallel.redistribute import (
    EpochFence,
    RedistributeConfig,
    RedistributeError,
    RedistributeStageFailure,
    assemble_from_survivors,
    paged_transfer,
    plan_redistribute,
    redistribute,
    relay_tree,
    reset_transfer_seq,
    tree_covered,
)
from accelerate_tpu.resilience.chaos import FaultPlan
from accelerate_tpu.resilience.membership import (
    EPOCH_KEY,
    DictStore,
    FilesystemStore,
    StaleEpochError,
)


def _devices():
    return np.asarray(jax.devices())


def _mesh(shape, axes, devices=None):
    devs = _devices() if devices is None else np.asarray(devices)
    return Mesh(devs[: int(np.prod(shape))].reshape(shape), axes)


class _Sink:
    """Minimal telemetry double: captures write_record payloads."""

    enabled = True

    def __init__(self):
        self.records = []

    def write_record(self, kind, payload):
        self.records.append({"kind": kind, **payload})
        return self.records[-1]


def _tree_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# -- round-trip property: random shapes/dtypes × random mesh pairs -------------


def test_roundtrip_random_shapes_meshes_bit_exact():
    """The property sweep: every (leaf set, mesh pair, spec pair) sampled
    must redistribute bit-exact on the staged rung, match the host-relay
    reference exactly (the tolerance-0 gate pinning staged == relay), and
    respect the scratch bound in the plan."""
    devs = _devices()
    rng = np.random.default_rng(0)
    mesh_pairs = [
        # reshard: same devices, different factoring
        (_mesh((4, 2), ("x", "y")), _mesh((2, 4), ("x", "y"))),
        # permute: same mesh shape over a rolled device order
        (
            _mesh((8,), ("x",)),
            Mesh(np.roll(devs, 3).reshape(8), ("x",)),
        ),
        # shrink-shaped: full mesh down to the first half
        (_mesh((8,), ("x",)), _mesh((4,), ("x",), devs[:4])),
        # regrow-shaped: half mesh back to the full mesh
        (_mesh((4,), ("x",), devs[:4]), _mesh((8,), ("x",))),
        # disjoint halves: no shared device at all
        (_mesh((4,), ("x",), devs[:4]), _mesh((4,), ("x",), devs[4:])),
    ]
    dtypes = [np.float32, np.int32, jnp.bfloat16]
    for trial, (mesh_a, mesh_b) in enumerate(mesh_pairs):
        specs_a = [P("x"), P(None), P(None, "x") if len(mesh_a.shape) == 1 else P("y", "x")]
        specs_b = [P(None), P("x"), P("x", None)]
        tree = {}
        dst = {}
        for i in range(3):
            dims = rng.integers(1, 3 + 1)
            # multiples of 8 so every factoring divides; +1-d leaves mix in
            shape = tuple(int(8 * rng.integers(1, 5)) for _ in range(dims))
            dtype = dtypes[int(rng.integers(0, len(dtypes)))]
            value = rng.standard_normal(shape).astype(dtype)
            spec_a = specs_a[int(rng.integers(0, len(specs_a)))]
            spec_b = specs_b[int(rng.integers(0, len(specs_b)))]
            # clip specs to the leaf's rank
            spec_a = P(*spec_a[: len(shape)])
            spec_b = P(*spec_b[: len(shape)])
            tree[f"leaf{i}"] = jax.device_put(value, NamedSharding(mesh_a, spec_a))
            dst[f"leaf{i}"] = NamedSharding(mesh_b, spec_b)
        config = RedistributeConfig(max_scratch_bytes=512)  # force chunking
        plan = plan_redistribute(tree, dst, config=config)
        assert plan.rung == "staged", trial
        for stage in plan.stages:
            # every chunked stage respects the bound unless it is the
            # unchunkable floor: one slab per destination partition of the
            # axis (at most one row per device on the 8-way simulation)
            if stage.chunk is not None and stage.chunk[2] > len(jax.devices()):
                assert stage.nbytes <= config.max_scratch_bytes, (trial, stage)
        assert plan.peak_scratch_bytes == max(
            (s.nbytes for s in plan.stages), default=0
        )
        out = redistribute(tree, dst, config=config)
        ref = relay_tree(tree, set(), None, dst)
        for key in tree:
            assert np.array_equal(np.asarray(out[key]), np.asarray(tree[key])), (trial, key)
            assert np.array_equal(np.asarray(out[key]), np.asarray(ref[key])), (trial, key)
            assert out[key].sharding == dst[key], (trial, key)


def test_plan_is_metadata_only_and_kinds_match_geometry():
    devs = _devices()
    mesh = _mesh((8,), ("x",))
    rolled = Mesh(np.roll(devs, 1).reshape(8), ("x",))
    half_a = _mesh((4,), ("x",), devs[:4])
    half_b = _mesh((4,), ("x",), devs[4:])
    x = jax.device_put(np.arange(64, dtype=np.float32), NamedSharding(mesh, P("x")))
    h = jax.device_put(np.arange(32, dtype=np.float32), NamedSharding(half_a, P("x")))
    plan = plan_redistribute(
        {"permute": x, "reshard": x, "cross": h},
        {
            "permute": NamedSharding(rolled, P("x")),  # same tiling, new owners
            "reshard": NamedSharding(mesh, P(None)),  # tiling changes
            "cross": NamedSharding(half_b, P("x")),  # disjoint devices
        },
    )
    assert plan.stage_kinds == {
        "collective_permute": 1, "all_to_all": 1, "device_put": 1,
    }
    # identity leaves plan zero stages
    plan_id = plan_redistribute({"x": x}, {"x": NamedSharding(mesh, P("x"))})
    assert plan_id.rung == "staged" and len(plan_id.stages) == 0


def test_rung_decision_lost_devices_and_buddy_force_relay():
    devs = _devices()
    mesh = _mesh((8,), ("x",))
    x = jax.device_put(np.arange(64, dtype=np.float32), NamedSharding(mesh, P("x")))
    dst = {"x": NamedSharding(_mesh((4,), ("x",), devs[:4]), P("x"))}
    plan = plan_redistribute({"x": x}, dst, lost_device_ids={devs[7].id})
    assert plan.rung == "host_relay"
    assert not plan.covered  # a lost shard with no buddy does not cover
    plan2 = plan_redistribute({"x": x}, dst, buddy_tree={"x": x})
    assert plan2.rung == "host_relay" and plan2.covered
    # and redistribute() on an uncovered plan fails loud, before moving bytes
    with pytest.raises(RedistributeError, match="do not cover|no rung"):
        redistribute({"x": x}, dst, lost_device_ids={devs[7].id})


def test_shrink_path_matches_legacy_relay_bit_exact():
    """The elastic shrink shape: replicated buddy covers a lost shard; the
    primitive's relay rung must equal relay_tree exactly (it IS relay_tree,
    behind the plan step)."""
    devs = _devices()
    mesh = _mesh((8,), ("x",))
    rolled = Mesh(np.roll(devs, 1).reshape(8), ("x",))
    value = np.arange(128, dtype=np.float32)
    primary = jax.device_put(value, NamedSharding(mesh, P("x")))
    buddy = jax.device_put(value, NamedSharding(rolled, P("x")))
    lost = {devs[0].id}
    survivors = _mesh((4,), ("x",), devs[4:])
    dst = NamedSharding(survivors, P("x"))
    assert tree_covered([primary], lost, [buddy])
    out = redistribute(
        [primary], [dst], lost_device_ids=lost, buddy_tree=[buddy]
    )
    ref = relay_tree([primary], lost, [buddy], [dst])
    assert np.array_equal(np.asarray(out[0]), value)
    assert np.array_equal(np.asarray(out[0]), np.asarray(ref[0]))


# -- transaction + chaos ladder ------------------------------------------------


def test_chaos_killed_stage_falls_back_source_intact_telemetry_recorded():
    reset_transfer_seq()
    mesh_a = _mesh((4, 2), ("x", "y"))
    mesh_b = _mesh((2, 4), ("x", "y"))
    value = np.random.default_rng(1).standard_normal((64, 32)).astype(np.float32)
    tree = {"w": jax.device_put(value, NamedSharding(mesh_a, P("x", "y")))}
    dst = {"w": NamedSharding(mesh_b, P("y", None))}
    config = RedistributeConfig(max_scratch_bytes=1024)
    plan = FaultPlan(redistribute_fail_at=(0,), redistribute_fail_stage=2)
    sink = _Sink()
    out = redistribute(tree, dst, config=config, fault_plan=plan, telemetry=sink)
    # ladder ran staged → host relay; the source was never corrupted
    assert np.array_equal(np.asarray(tree["w"]), value)
    assert np.array_equal(np.asarray(out["w"]), value)
    assert out["w"].sharding == dst["w"]
    [record] = sink.records
    assert record["kind"] == "redistribute"
    assert record["outcome"] == "fell_back"
    assert record["failed_stage"] == 2
    assert record["failed_stage_kind"] == "all_to_all"
    assert record["path"] == "staged"
    # the chaos ledger names the stage it killed
    assert plan.events and plan.events[0]["fault"] == "redistribute_fail"
    assert plan.events[0]["stage"] == 2


def test_forced_staged_chaos_fails_loud_naming_the_stage():
    reset_transfer_seq()
    mesh_a = _mesh((8,), ("x",))
    tree = {"w": jax.device_put(np.zeros(64, np.float32), NamedSharding(mesh_a, P("x")))}
    dst = {"w": NamedSharding(mesh_a, P(None))}
    plan = FaultPlan(redistribute_fail_at=(0,), redistribute_fail_stage=0)
    sink = _Sink()
    with pytest.raises(RedistributeError, match="stage 0"):
        redistribute(
            tree, dst, config=RedistributeConfig(force_path="staged"),
            fault_plan=plan, telemetry=sink,
        )
    assert sink.records[-1]["outcome"] == "failed"
    assert sink.records[-1]["failed_stage"] == 0


def test_chaos_env_vars_arm_redistribute_legs(monkeypatch):
    monkeypatch.setenv("ACCELERATE_CHAOS_REDISTRIBUTE_FAIL_AT", "0,3")
    monkeypatch.setenv("ACCELERATE_CHAOS_REDISTRIBUTE_FAIL_STAGE", "2")
    plan = FaultPlan.from_env()
    assert plan.redistribute_fail_at == (0, 3)
    assert plan.redistribute_fail_stage == 2
    assert plan.active


def test_steady_state_transfer_compiles_nothing_second_time():
    from accelerate_tpu.telemetry.compile_tracker import CompileTracker

    mesh_a = _mesh((4, 2), ("x", "y"))
    mesh_b = _mesh((2, 4), ("x", "y"))
    value = np.random.default_rng(2).standard_normal((64, 32)).astype(np.float32)
    tree = {"w": jax.device_put(value, NamedSharding(mesh_a, P("x", "y")))}
    dst = {"w": NamedSharding(mesh_b, P("y", None))}
    config = RedistributeConfig(max_scratch_bytes=1024)
    redistribute(tree, dst, config=config)  # warm the program caches
    tracker = CompileTracker().start()
    out = redistribute(tree, dst, config=config)
    assert tracker.compile_count == 0, "steady-state redistribute recompiled"
    assert np.array_equal(np.asarray(out["w"]), value)


# -- epoch-fenced commit -------------------------------------------------------


def test_zombie_transfer_refused_at_commit_and_recorded():
    store = DictStore()
    store.write(EPOCH_KEY, {"epoch": 3, "members": [0, 1]})
    mesh = _mesh((8,), ("x",))
    value = np.arange(64, dtype=np.float32)
    tree = {"w": jax.device_put(value, NamedSharding(mesh, P("x")))}
    dst = {"w": NamedSharding(mesh, P(None))}
    fence = EpochFence(store, epoch=3)
    sink = _Sink()

    # the epoch moves WHILE the transfer is in flight (probe = mid-stage)
    def _move_epoch():
        store.write(EPOCH_KEY, {"epoch": 4, "members": [1]})

    with pytest.raises(StaleEpochError):
        redistribute(
            tree, dst, epoch_fence=fence, probe=_move_epoch, telemetry=sink
        )
    assert sink.records[-1]["outcome"] == "stale_epoch_write_rejected"
    # source untouched by the refused commit
    assert np.array_equal(np.asarray(tree["w"]), value)
    # a fence at the CURRENT epoch commits fine
    out = redistribute(tree, dst, epoch_fence=EpochFence(store, epoch=4))
    assert np.array_equal(np.asarray(out["w"]), value)


# -- the handoff wire ----------------------------------------------------------


def test_paged_transfer_probe_fires_and_chaos_kills_named_stage():
    reset_transfer_seq()
    fired = []

    def extract(pages):
        k = np.zeros((len(pages), 2, 4, 2, 8), np.float32)
        return k, k.copy()

    kb, vb = paged_transfer(
        extract, [0, 1, 2], probe=lambda: fired.append(True), fault_plan=None,
    )
    assert fired and kb.shape[0] == 3
    reset_transfer_seq()
    plan = FaultPlan(redistribute_fail_at=(0,), redistribute_fail_stage=1)
    with pytest.raises(RedistributeStageFailure, match="stage 1"):
        paged_transfer(extract, [0, 1, 2], fault_plan=plan)
    assert plan.events[0]["fault"] == "redistribute_fail"


def test_paged_transfer_telemetry_carries_trace_id():
    reset_transfer_seq()

    def extract(pages):
        k = np.zeros((len(pages), 2, 4, 2, 8), np.float32)
        return k, k

    sink = _Sink()
    paged_transfer(extract, [0, 1], telemetry=sink, trace_id="req-42")
    [record] = sink.records
    assert record["kind"] == "redistribute"
    assert record["trace_id"] == "req-42"
    assert record["stages"] == 2
    assert record["outcome"] == "committed"
    assert record["bytes_moved"] > 0


# -- elastic re-exports keep their import path ---------------------------------


def test_elastic_reexports_are_the_primitive():
    from accelerate_tpu.resilience import elastic

    assert elastic.relay_tree is relay_tree
    assert elastic.tree_covered is tree_covered
    assert elastic.assemble_from_survivors is assemble_from_survivors


# -- the CAS store (satellite) -------------------------------------------------


def test_dictstore_roundtrip_matches_filesystem(tmp_path):
    for store in (DictStore(), FilesystemStore(str(tmp_path))):
        store.write("hosts/0", {"beat": 1})
        store.write("hosts/1", {"beat": 2})
        assert store.read("hosts/0") == {"beat": 1}
        assert store.read("missing") is None
        assert store.list("hosts") == {"hosts/0": {"beat": 1}, "hosts/1": {"beat": 2}}
        store.delete("hosts/0")
        assert store.read("hosts/0") is None
        store.delete("hosts/0")  # idempotent


def test_dictstore_cas_semantics_match_filesystem(tmp_path):
    """The fenced API behaves identically across backends: stale fenced
    writes refused, mint with wrong expectation refused, mint with the right
    expectation advances — the drop-in contract a GCS/etcd backend needs."""
    for store in (DictStore(), FilesystemStore(str(tmp_path))):
        store.write(EPOCH_KEY, {"epoch": 2, "members": [0, 1]})
        with pytest.raises(StaleEpochError):
            store.fenced_write("hosts/0", {"beat": 1}, epoch=1)
        store.fenced_write("hosts/0", {"beat": 1}, epoch=2)  # current: fine
        with pytest.raises(StaleEpochError):
            store.mint_epoch({"epoch": 9, "members": [0]}, expected=1)
        store.mint_epoch({"epoch": 3, "members": [0]}, expected=2)
        assert store.read(EPOCH_KEY)["epoch"] == 3


def test_dictstore_mint_race_exactly_one_winner():
    """Real CAS: N threads race the same expected-epoch mint; the lock makes
    the read-check-write atomic so exactly one mint wins and every loser
    gets StaleEpochError (the loser then re-reads and finds the work done —
    the MembershipService resolve_loss contract)."""
    import threading

    store = DictStore()
    store.write(EPOCH_KEY, {"epoch": 1, "members": [0, 1, 2, 3]})
    outcomes = []
    barrier = threading.Barrier(8)

    def _mint(i):
        barrier.wait()
        try:
            store.mint_epoch({"epoch": 2, "members": [0, 1], "minter": i}, expected=1)
            outcomes.append(("won", i))
        except StaleEpochError:
            outcomes.append(("lost", i))

    threads = [threading.Thread(target=_mint, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wins = [o for o in outcomes if o[0] == "won"]
    assert len(wins) == 1, outcomes
    assert store.read(EPOCH_KEY)["minter"] == wins[0][1]
