"""cloud-launch command assembly (the reference SageMaker launcher analogue,
commands/launch.py:871-888 — submission into a managed cloud fleet)."""

import argparse
import subprocess
import sys

import pytest

from accelerate_tpu.commands.cloud import (
    delete_command,
    plan,
    provision_command,
    run,
    train_command,
)


def _args(**over):
    base = dict(
        tpu_name="trainer", zone="us-central2-b", accelerator_type="v5litepod-8",
        runtime_version="tpu-ubuntu2204-base", project=None, queued=False,
        spot=False, setup_cmd=None, env=[], delete_after=False, debug=True,
        mixed_precision=None, training_script="train.py", training_script_args=[],
    )
    base.update(over)
    return argparse.Namespace(**base)


def test_direct_plan_creates_pushes_runs():
    steps = plan(_args())
    assert steps[0][:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
    assert "scp" in steps[1]
    assert any("accelerate-tpu launch" in part for part in steps[2])
    assert len(steps) == 3  # no delete without --delete_after


def test_queued_plan_waits_and_deletes():
    steps = plan(_args(queued=True, delete_after=True, spot=True))
    assert steps[0][1] == "alpha" and "queued-resources" in steps[0]
    assert "--spot" in steps[0]
    assert "describe" in steps[1]  # capacity wait
    assert "delete" in steps[-1] and "queued-resources" in steps[-1]


def test_train_command_env_and_args():
    cmd = train_command(_args(
        env=["WANDB_MODE=offline"], mixed_precision="bf16",
        training_script_args=["--epochs", "3"],
    ))
    remote = cmd[-1]
    assert "export WANDB_MODE=offline" in remote
    assert "--mixed_precision bf16" in remote
    assert "~/train.py --epochs 3" in remote
    assert "--worker=all" in cmd


def test_rejects_non_python_script():
    with pytest.raises(ValueError, match="python training script"):
        run(_args(training_script="train.sh"))


def test_env_validation():
    with pytest.raises(ValueError, match="KEY=VALUE"):
        train_command(_args(env=["BROKEN"]))


def test_env_key_must_be_identifier():
    # the key lands unquoted in the remote shell line — metacharacters would
    # inject into the ssh command
    with pytest.raises(ValueError, match="identifier"):
        train_command(_args(env=["A B=x"]))
    with pytest.raises(ValueError, match="identifier"):
        train_command(_args(env=["$(reboot)=x"]))


def test_cli_debug_prints_plan():
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.cli", "cloud-launch",
         "--tpu_name", "t", "--zone", "z", "--debug", "--delete_after", "train.py"],
        capture_output=True, text=True,
    )
    assert result.returncode == 0, result.stderr
    lines = result.stdout.strip().splitlines()
    assert lines[0].startswith("gcloud compute tpus tpu-vm create")
    assert "delete" in lines[-1]


def test_delete_after_runs_on_failure(monkeypatch):
    """--delete_after is job semantics: teardown runs even when a step fails
    (a stranded slice keeps billing)."""
    import accelerate_tpu.commands.cloud as cloud

    calls = []

    class R:
        def __init__(self, rc):
            self.returncode = rc

    def fake_run(cmd, **kw):
        calls.append(cmd)
        # provision ok, scp FAILS, delete must still run
        return R(1 if "scp" in cmd else 0)

    monkeypatch.setattr(cloud.subprocess, "run", fake_run)
    monkeypatch.setattr(cloud.shutil, "which", lambda name: "/usr/bin/gcloud")
    args = _args(debug=False, delete_after=True)
    with pytest.raises(RuntimeError, match="command failed"):
        run(args)
    assert any("delete" in c for c in calls), calls


def test_train_command_joins_with_and():
    cmd = train_command(_args(setup_cmd="pip install -e .", env=["A=1"]))
    assert " && " in cmd[-1] and "; " not in cmd[-1]
