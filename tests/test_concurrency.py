"""Tier-1 tests for the concurrency sanitizer (analysis/concurrency.py) and
the concurrency lint rule family (analysis/lint.py).

The two seeded fixtures here — a lock-order inversion and a sleep-under-lock
— are the acceptance proof that the detector names real hazards
(``CONCURRENCY_CYCLE``, ``LOCK_BLOCKING_HOLD``), and the drill tests prove
the codebase's own 8-lock surface runs clean under the recorder and matches
``tests/contracts/concurrency.json`` exactly. Fixture locks are ``forget()``-
ed on the way out so they never leak into that exact inventory.
"""

import json
import os
import shutil
import threading
import time

from accelerate_tpu.analysis.concurrency import (
    ConcurrencyContract,
    _find_cycles,
    gate_concurrency,
    named_lock,
    record,
    registry,
)
from accelerate_tpu.analysis.lint import lint_source

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONTRACTS_DIR = os.path.join(REPO_ROOT, "tests", "contracts")


# -- the registry / named locks ------------------------------------------------


def test_named_lock_basics():
    lock = named_lock("test.basic")
    try:
        assert not lock.locked()
        with lock:
            assert lock.locked()
            assert "test.basic" in repr(lock)
        assert not lock.locked()
        assert lock.acquire(blocking=False)
        lock.release()
        assert "test.basic" in registry().lock_names()
    finally:
        registry().forget("test.basic")
    assert "test.basic" not in registry().lock_names()


def test_held_stack_survives_out_of_order_release():
    a, b = named_lock("test.ooo_a"), named_lock("test.ooo_b")
    try:
        a.acquire()
        b.acquire()
        a.release()  # not LIFO — the stack must pop by name, not position
        b.release()
        assert not a.locked() and not b.locked()
    finally:
        registry().forget("test.ooo_a", "test.ooo_b")


def test_seeded_lock_inversion_detected():
    """The acceptance fixture: A->B in one thread, B->A in another, is a
    CONCURRENCY_CYCLE naming both locks."""
    a, b = named_lock("test.inv_a"), named_lock("test.inv_b")
    try:
        registry().reset_observations()
        with record():

            def forward():
                with a:
                    with b:
                        pass

            def backward():
                with b:
                    with a:
                        pass

            # sequential threads: the ORDER graph doesn't need a live
            # deadlock, just both orders observed
            for target in (forward, backward):
                t = threading.Thread(target=target)
                t.start()
                t.join()
        report = registry().report()
        cycles = [f for f in report.findings if f.code == "CONCURRENCY_CYCLE"]
        assert cycles, report.render()
        assert "test.inv_a" in cycles[0].message
        assert "test.inv_b" in cycles[0].message
        assert report.inventory["cycles"] == [["test.inv_a", "test.inv_b"]]
    finally:
        registry().forget("test.inv_a", "test.inv_b")


def test_seeded_sleep_under_lock_detected():
    """The acceptance fixture: time.sleep inside ``with lock:`` under the
    recorder is a LOCK_BLOCKING_HOLD naming the lock and the boundary."""
    guard = named_lock("test.sleepy")
    try:
        registry().reset_observations()
        with record():
            with guard:
                time.sleep(0.001)
        report = registry().report()
        holds = [f for f in report.findings if f.code == "LOCK_BLOCKING_HOLD"]
        assert holds, report.render()
        assert any(
            "test.sleepy" in f.message and "time.sleep" in f.message for f in holds
        )
    finally:
        registry().forget("test.sleepy")


def test_blocking_without_lock_is_clean():
    registry().reset_observations()
    with record():
        time.sleep(0.001)  # no lock held: not a hold
    report = registry().report()
    assert [f for f in report.findings if f.code == "LOCK_BLOCKING_HOLD"] == []


def test_record_restores_patches_on_exit():
    original_sleep, original_fsync = time.sleep, os.fsync
    with record():
        assert time.sleep is not original_sleep
        assert os.fsync is not original_fsync
    assert time.sleep is original_sleep
    assert os.fsync is original_fsync


def test_recording_off_records_no_edges():
    a, b = named_lock("test.off_a"), named_lock("test.off_b")
    try:
        registry().reset_observations()
        with a:
            with b:
                pass
        assert ("test.off_a", "test.off_b") not in registry().edges()
    finally:
        registry().forget("test.off_a", "test.off_b")


def test_find_cycles_unit():
    assert _find_cycles({("A", "B"), ("B", "A")}) == [["A", "B"]]
    assert _find_cycles({("A", "B"), ("B", "C"), ("C", "A")}) == [["A", "B", "C"]]
    assert _find_cycles({("A", "B"), ("B", "C")}) == []


# -- the contract --------------------------------------------------------------


def _seeded_report(locks=("x",), cycles=(), blocking=()):
    from accelerate_tpu.analysis.findings import AnalysisReport

    report = AnalysisReport(meta={"label": "concurrency", "kind": "concurrency"})
    report.inventory = {
        "locks": sorted(locks),
        "cycles": [list(c) for c in cycles],
        "blocking_holds": [
            {"lock": lock, "kind": kind, "count": 1} for lock, kind in blocking
        ],
    }
    return report


def test_contract_roundtrip_and_drift(tmp_path):
    report = _seeded_report(locks=["a", "b"])
    contract = ConcurrencyContract.from_report(report)
    path = str(tmp_path / "concurrency.json")
    contract.save(path)
    loaded = ConcurrencyContract.load(path)
    assert loaded.check(report) == []

    drifted = loaded.check(_seeded_report(locks=["a", "b", "c"]))
    assert [f.path for f in drifted] == ["concurrency:locks"]
    assert "new locks ['c']" in drifted[0].message

    drifted = loaded.check(
        _seeded_report(locks=["a", "b"], cycles=[["a", "b"]], blocking=[("a", "time.sleep")])
    )
    assert sorted(f.path for f in drifted) == [
        "concurrency:blocking_holds",
        "concurrency:cycles",
    ]


def test_gate_concurrency_update_is_churn_free(tmp_path):
    report = _seeded_report(locks=["a"])
    notes = gate_concurrency(report, str(tmp_path), update=True)
    assert [f.code for f in notes] == ["CONTRACT_UPDATED"]
    written = (tmp_path / "concurrency.json").read_bytes()
    # second update with an undrifted report: byte-identical, no note
    assert gate_concurrency(report, str(tmp_path), update=True) == []
    assert (tmp_path / "concurrency.json").read_bytes() == written
    assert gate_concurrency(report, str(tmp_path)) == []


def test_gate_concurrency_missing_contract(tmp_path):
    notes = gate_concurrency(_seeded_report(), str(tmp_path))
    assert [f.code for f in notes] == ["CONTRACT_MISSING"]


# -- the lint rule family ------------------------------------------------------


def test_lint_bare_acquire_flagged_and_guarded_forms_clean():
    bad = "def f(lock):\n    lock.acquire()\n    work()\n"
    assert [f.code for f in lint_source(bad)] == ["LOCK_BARE_ACQUIRE"]
    good = (
        "def f(lock):\n"
        "    lock.acquire()\n"
        "    try:\n"
        "        work()\n"
        "    finally:\n"
        "        lock.release()\n"
    )
    assert lint_source(good) == []
    with_form = "def f(lock):\n    with lock:\n        work()\n"
    assert lint_source(with_form) == []


def test_lint_blocking_call_under_lock():
    bad = (
        "import time\n"
        "def f(self):\n"
        "    with self._write_lock:\n"
        "        time.sleep(1)\n"
    )
    assert [f.code for f in lint_source(bad)] == ["LOCK_BLOCKING_CALL"]
    # a nested def under the lock runs LATER, off the lock's critical section
    deferred = (
        "def f(self):\n"
        "    with self._lock:\n"
        "        def later():\n"
        "            time.sleep(1)\n"
        "        schedule(later)\n"
    )
    assert lint_source(deferred) == []
    # named_lock-assigned names are lockish even without 'lock' in the name
    named = (
        "from accelerate_tpu.analysis.concurrency import named_lock\n"
        "guard = named_lock('a.b')\n"
        "def f(fd):\n"
        "    import os\n"
        "    with guard:\n"
        "        os.fsync(fd)\n"
    )
    assert [f.code for f in lint_source(named)] == ["LOCK_BLOCKING_CALL"]


def test_lint_thread_shared_mutation():
    bad = (
        "import threading\n"
        "class W:\n"
        "    def _run(self):\n"
        "        self.fired = True\n"
        "    def arm(self):\n"
        "        self.fired = False\n"
        "        threading.Thread(target=self._run).start()\n"
    )
    assert [f.code for f in lint_source(bad)] == ["THREAD_SHARED_MUTATION"]
    guarded = (
        "import threading\n"
        "class W:\n"
        "    def _run(self):\n"
        "        with self._lock:\n"
        "            self.fired = True\n"
        "    def arm(self):\n"
        "        with self._lock:\n"
        "            self.fired = False\n"
        "        threading.Thread(target=self._run).start()\n"
    )
    assert lint_source(guarded) == []


def test_lint_async_np_view():
    bad = (
        "import jax\n"
        "step = jax.jit(fn)\n"
        "def loop(pages):\n"
        "    pages[0] = 1\n"
        "    step(pages[0])\n"
    )
    assert [f.code for f in lint_source(bad)] == ["ASYNC_NP_VIEW"]
    copied = bad.replace("step(pages[0])", "step(pages[0].copy())")
    assert lint_source(copied) == []


def test_lint_unregistered_lock():
    bad = "import threading\nlock = threading.Lock()\n"
    assert [f.code for f in lint_source(bad)] == ["LOCK_UNREGISTERED"]
    wrapped = (
        "import threading\n"
        "from accelerate_tpu.analysis.concurrency import named_lock\n"
        "lock = named_lock('x.y', inner=threading.Lock())\n"
    )
    assert lint_source(wrapped) == []


def test_lint_unused_waiver_audited():
    stale = "x = 1  # accel-lint: disable=HOST_RNG_IN_TRACE\n"
    assert [f.code for f in lint_source(stale)] == ["LINT_WAIVER_UNUSED"]
    used = (
        "import threading\n"
        "lock = threading.Lock()  # accel-lint: disable=LOCK_UNREGISTERED\n"
    )
    assert lint_source(used) == []


# -- HazardSanitizer patch plumbing under concurrency --------------------------


def test_sanitizer_concurrent_enter_exit_two_threads():
    """Satellite: _install_patches/_remove_patches refcount under two
    threads opening and closing sanitizer windows concurrently — depth must
    come back to zero and every patched attribute must be restored."""
    import jax

    from accelerate_tpu.analysis import sanitizer as san

    original_device_get = jax.device_get
    errors: list = []

    def worker():
        try:
            for _ in range(25):
                with san.HazardSanitizer(label="t"):
                    pass
        except Exception as exc:  # pragma: no cover - failure detail
            errors.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    assert san._patch_depth == 0
    assert jax.device_get is original_device_get
    assert san._patch_originals == {}


# -- the drill + gate ----------------------------------------------------------


def test_drill_runs_clean_and_matches_contract():
    """The real fleet + elastic chaos-path drill under the recorder: zero
    cycles, zero blocking holds (the hub fsync fix is load-bearing here —
    finish() under the old shape held hub.write across os.fsync), and the
    lock inventory matches the checked-in contract exactly."""
    from accelerate_tpu.commands.analyze import _concurrency_drill

    report = _concurrency_drill()
    assert report.findings == [], report.render()
    assert report.inventory["cycles"] == []
    assert report.inventory["blocking_holds"] == []
    assert report.inventory["acquisitions"] > 0
    assert gate_concurrency(report, CONTRACTS_DIR) == [], report.inventory["locks"]

    contract = ConcurrencyContract.load(
        os.path.join(CONTRACTS_DIR, "concurrency.json")
    )
    assert contract.cycles == 0
    assert contract.blocking_holds == 0
    assert len(contract.locks) == 8


def test_hub_finish_does_not_hold_lock_across_fsync(tmp_path):
    """Regression pin for the satellite-6 fix: the hub's finish() path
    flushes + fsyncs OUTSIDE hub.write. Under the recorder, a write + finish
    must produce no blocking hold attributed to hub.write."""
    from accelerate_tpu.telemetry.hub import Telemetry, TelemetryConfig

    registry().reset_observations()
    with record():
        hub = Telemetry(
            config=TelemetryConfig(enabled=True, dir=str(tmp_path), flush_every=0)
        )
        hub.write_record("test", {"payload": 1})
        hub.finish()
    held = [b for b in registry().blocking_holds() if b["lock"] == "hub.write"]
    assert held == [], held
    registry().reset_observations()


def test_cli_exits_1_on_tampered_concurrency_contract(tmp_path, capsys):
    """End-to-end: a contracts dir whose concurrency.json expects a lock
    that does not exist must fail `analyze --self-check --contracts` with
    exit 1, naming the drifted field."""
    tampered_dir = tmp_path / "contracts"
    shutil.copytree(CONTRACTS_DIR, tampered_dir)
    path = tampered_dir / "concurrency.json"
    payload = json.loads(path.read_text())
    payload["expectations"]["locks"].append("ghost.lock")
    path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")

    from accelerate_tpu.commands.cli import main

    rc = main(
        ["analyze", "--self-check", "--no-compile", "--contracts",
         "--contracts-dir", str(tampered_dir)]
    )
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "concurrency:locks" in out
    assert "ghost.lock" in out
