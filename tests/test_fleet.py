"""Serving fleet layer: health-aware routing over N engine replicas.

The acceptance drills from the fleet PR, all tier-1-fast on the CPU mesh:
least-loaded placement, the replica-SIGKILL mid-decode drill (every offered
request reaches a terminal state exactly once, failed-over outputs bit-exact
at temperature 0), graceful drain with queue re-homing, heartbeat-loss
failover, router-level backpressure, the health state machine, and the
engine-side drain/snapshot/cancel hooks the router builds on.
"""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import Llama
from accelerate_tpu.models.generation import generate
from accelerate_tpu.resilience import FaultPlan, is_fleet_transient
from accelerate_tpu.serving import (
    EngineReplica,
    HealthPolicy,
    QueueFull,
    ReplicaLost,
    ReplicaState,
    ServingEngine,
    ServingRouter,
    run_offered_load,
)
from accelerate_tpu.telemetry import CompileTracker
from accelerate_tpu.telemetry.serving import ServingStats, fleet_rollup


@pytest.fixture(scope="module")
def llama():
    model = Llama("llama-tiny")
    return model, model.init(jax.random.key(0))


def _prompts(lengths, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (s,)).astype(np.int32) for s in lengths]


def _router(llama, n=2, fault_plan=None, telemetry=None, health=None,
            max_failovers=2, **engine_kwargs):
    model, params = llama
    kwargs = {"num_slots": 2, "max_len": 64, **engine_kwargs}
    return ServingRouter(
        engine_factory=lambda: ServingEngine(model, params, **kwargs),
        num_replicas=n,
        fault_plan=fault_plan,
        telemetry=telemetry,
        health=health,
        max_failovers=max_failovers,
    )


# -- the acceptance invariants ------------------------------------------------


def test_routed_generate_bit_equal_single_engine(llama):
    """Temperature-0 outputs through a 2-replica routed fleet are bit-equal
    to one engine's — continuous batching AND replication are invisible."""
    model, params = llama
    prompts = _prompts([3, 7, 12, 5, 9, 4])
    single = ServingEngine(model, params, num_slots=2, max_len=64, eos_token_id=5)
    ref = single.generate_many(prompts, max_new_tokens=6)
    router = _router(llama, eos_token_id=5)
    outs = router.generate_many(prompts, max_new_tokens=6)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, b)
    # the fleet actually spread the load — this wasn't one replica doing it
    assert all(p > 0 for p in router.placements)


def test_replica_kill_mid_decode_drill(llama, tmp_path):
    """The headline drill: FaultPlan SIGKILLs 1 of 2 replicas mid-stream.
    Every submitted request reaches a terminal state EXACTLY once (zero
    lost, zero duplicated), failed-over requests re-prefill and finish
    bit-exactly (temp 0), and the death/failover trail lands in
    telemetry.jsonl with no duplicate terminal events."""
    from accelerate_tpu.telemetry import Telemetry, TelemetryConfig

    model, params = llama
    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    plan = FaultPlan(replica_kill_step=3, replica_kill_index=0)
    router = _router(llama, fault_plan=plan, telemetry=hub)
    prompts = _prompts([3, 7, 12, 5, 9, 4], seed=1)
    rids = [router.submit(p, max_new_tokens=6) for p in prompts]

    results = []  # via step(), not run(): a dict would hide duplicates
    while router.busy:
        results.extend(router.step())
    assert router.replica_deaths == 1
    assert router.replicas[0].state is ReplicaState.DEAD
    assert router.failovers > 0

    seen = [r.request_id for r in results if r.request_id in set(rids)]
    assert sorted(seen) == sorted(rids)  # all terminated, none twice
    by_id = {r.request_id: r for r in results}
    assert all(
        by_id[rid].finish_reason in ("eos", "length", "expired") for rid in rids
    )
    # failover is invisible in the tokens: re-prefill regenerates exactly
    for p, rid in zip(prompts, rids):
        expected = np.asarray(generate(model, params, p[None], max_new_tokens=6))[0][p.size:]
        np.testing.assert_array_equal(by_id[rid].generated, expected)

    router.flush_telemetry()
    hub.finish(flush=False)
    records = [json.loads(line) for line in open(tmp_path / "telemetry.jsonl")]
    deaths = [r for r in records if r["kind"] == "fleet" and r.get("event") == "replica_death"]
    assert len(deaths) == 1 and deaths[0]["replica"] == 0
    rehomes = [r for r in records if r["kind"] == "fleet" and r.get("event") == "rehome"]
    assert {r["request_id"] for r in rehomes} <= set(rids)
    assert len({r["request_id"] for r in rehomes}) == len(rehomes)  # no double re-home
    fleet = [r for r in records if r["kind"] == "fleet" and "fleet" in r]
    assert fleet and fleet[-1]["fleet"]["replica_deaths"] == 1


def test_placement_picks_least_loaded_replica(llama):
    """Under skewed load the router places on the emptier replica — live
    ServingStats (queue depth + occupancy), not round-robin."""
    router = _router(llama, max_queue=8)
    # skew: pile work directly onto replica 0 behind the router's back
    for p in _prompts([4, 4, 4], seed=2):
        router.replicas[0].engine.submit(p, max_new_tokens=4)
    for p in _prompts([4, 4], seed=3):
        router.submit(p, max_new_tokens=4)
    assert router.placements == [0, 2]  # both routed submits avoided the pile
    assert router.replicas[1].engine.scheduler.waiting == 2
    router.run()


def test_routed_fleet_zero_steady_state_recompiles(llama):
    """Replication never costs a recompile: after one replica warms the
    shared model cache, every other replica runs on cache hits, and routed
    steady-state traffic compiles NOTHING — the per-replica
    serving_steady_state_compile_count == 0 gate."""
    _, params = llama
    model = Llama("llama-tiny")  # fresh instance: clean jit cache
    router = ServingRouter(
        engine_factory=lambda: ServingEngine(
            model, params, num_slots=2, max_len=64, buckets=(8, 16, 32)
        ),
        num_replicas=2,
    )
    tracker = CompileTracker().start()
    router.warmup()
    warm = tracker.snapshot()
    # ONE replica's worth of programs: decode + one prefill per bucket (the
    # paged engine scatters prefill pages directly — no insert programs; a
    # dense engine would add one insert per bucket) + the handoff pair
    # (page extract + adopt-insert, paged only — steady-state handoffs must
    # compile nothing). The second replica's warmup hit the shared cache for
    # every one of them.
    engine = router.replicas[0].engine
    per_bucket = 1 if engine.paged else 2
    handoff_pair = 2 if engine.paged else 0
    assert warm["jit_cache_misses"] == 1 + per_bucket * len(engine.buckets) + handoff_pair
    router.generate_many(_prompts([3, 9, 20, 31, 6, 14], seed=4), max_new_tokens=4)
    steady = tracker.snapshot()
    tracker.stop()
    assert steady["compile_count"] == warm["compile_count"]
    assert steady["jit_cache_misses"] == warm["jit_cache_misses"]
    assert steady["jit_cache_hits"] > warm["jit_cache_hits"]


# -- failover machinery -------------------------------------------------------


def test_heartbeat_loss_fails_over(llama):
    """A silent replica is operationally dead: its requests re-home and the
    fleet serves them all."""
    plan = FaultPlan(heartbeat_loss_step=2, heartbeat_loss_index=1)
    router = _router(llama, fault_plan=plan)
    prompts = _prompts([3, 5, 7, 4], seed=5)
    rids = [router.submit(p, max_new_tokens=5) for p in prompts]
    results = router.run()
    assert router.replicas[1].state is ReplicaState.DEAD
    assert router.replicas[1].death_reason == "heartbeat lost"
    assert sorted(results) == sorted(rids)
    assert all(r.finish_reason == "length" for r in results.values())


def test_cancelled_request_is_not_resurrected_by_failover(llama):
    """cancel() acked, then the hosting replica dies before retiring the
    request: the router's re-home path must honor the cancellation (terminal
    'cancelled'), never resurrect the request on a survivor — the fleet-level
    version of the cancel-double-free promise."""
    router = _router(llama)
    rids = [router.submit(p, max_new_tokens=8) for p in _prompts([4, 5], seed=32)]
    router.step()
    on_r0 = next(rid for rid in rids if router._inflight[rid].replica == 0)
    assert router.cancel(on_r0)
    router._on_replica_death(router.replicas[0], "test kill")
    results = router.run()
    assert results[on_r0].finish_reason == "cancelled"
    other = next(rid for rid in rids if rid != on_r0)
    assert results[other].finish_reason == "length"
    assert router.failovers == 0 or results[other].finish_reason == "length"


def test_failover_budget_exhausted_fails_request(llama):
    """Past max_failovers a request fails instead of bouncing around the
    fleet forever — the router-level analogue of the engine's capped
    requeue."""
    router = _router(llama, max_failovers=0)
    rids = [router.submit(p, max_new_tokens=8) for p in _prompts([4, 5], seed=6)]
    router.step()
    router._on_replica_death(router.replicas[0], "test kill")
    router._on_replica_death(router.replicas[1], "test kill")
    results = router.run()
    assert sorted(results) == sorted(rids)
    assert all(r.finish_reason == "failed" for r in results.values())
    assert router.failed_failovers >= 1
    with pytest.raises(ReplicaLost, match="fleet is down"):
        router.submit(_prompts([3], seed=7)[0], max_new_tokens=2)


def test_router_backpressure_drains_to_siblings_before_shedding(llama):
    """One replica's overload spills to the other; QueueFull reaches the
    caller only when EVERY placeable replica is full — and then carries the
    fleet-minimum retry_after_s hint."""
    router = _router(llama, num_slots=1, max_queue=1)
    admitted = 0
    with pytest.raises(QueueFull) as exc_info:
        for p in _prompts([4] * 10, seed=8):
            router.submit(p, max_new_tokens=4)
            admitted += 1
    # 1 queue spot per replica: both filled before the fleet shed
    assert admitted == 2
    assert router.placements[0] >= 1 and router.placements[1] >= 1
    assert exc_info.value.retry_after_s is not None and exc_info.value.retry_after_s > 0
    assert router.router_sheds == 1
    router.run()


def test_drain_replica_rehomes_queue_and_dies_empty(llama):
    """Graceful retirement: a draining replica stops admitting, its queued
    requests re-home, its active slots finish in place, and it transitions
    DRAINING → DEAD('drained') once empty."""
    router = _router(llama, num_slots=1, max_queue=8)
    prompts = _prompts([4, 5, 6, 7], seed=9)
    rids = [router.submit(p, max_new_tokens=4) for p in prompts]
    router.step()  # one request active per replica, rest queued
    moved = router.drain_replica(0)
    assert moved >= 1
    assert router.replicas[0].state is ReplicaState.DRAINING
    with pytest.raises(QueueFull, match="draining"):
        router.replicas[0].engine.submit(prompts[0], max_new_tokens=2)
    results = router.run()
    assert sorted(results) == sorted(rids)
    assert all(r.finish_reason == "length" for r in results.values())
    assert router.replicas[0].state is ReplicaState.DEAD
    assert router.replicas[0].death_reason == "drained"
    # the drained-out requests are counted where they left
    assert router.replicas[0].engine.stats.requests_rehomed == moved


def test_revive_returns_replica_to_rotation(llama):
    """DEAD → RECOVERING → HEALTHY with a fresh engine; the replica serves
    again."""
    router = _router(llama)
    router.replicas[1].mark_dead("test")
    router.revive(1)
    assert router.replicas[1].state is ReplicaState.HEALTHY
    prompts = _prompts([3, 4, 5, 6], seed=10)
    rids = [router.submit(p, max_new_tokens=3) for p in prompts]
    results = router.run()
    assert sorted(results) == sorted(rids)
    assert router.placements[1] > 0


# -- health state machine -----------------------------------------------------


def test_health_state_machine_transitions(llama):
    """HEALTHY → DEGRADED on degradation events, → DRAINING when they
    persist, DEGRADED → HEALTHY after clean steps."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    policy = HealthPolicy(degrade_after=1, recover_after=2, drain_after=3)
    replica = EngineReplica(0, engine, policy=policy)
    assert replica.state is ReplicaState.HEALTHY and replica.placeable

    engine.stats.record_watchdog_trip()
    replica.observe_step()
    assert replica.state is ReplicaState.DEGRADED
    assert replica.placeable  # degraded still serves, just deprioritized

    replica.observe_step()
    replica.observe_step()  # two clean observations
    assert replica.state is ReplicaState.HEALTHY

    engine.stats.record_quarantine()
    replica.observe_step()
    assert replica.state is ReplicaState.DEGRADED
    engine.stats.record_quarantine()
    engine.stats.record_watchdog_trip()
    replica.observe_step()  # cumulative events >= drain_after
    assert replica.state is ReplicaState.DRAINING
    assert not replica.placeable

    replica.mark_dead("test")
    assert replica.state is ReplicaState.DEAD and not replica.alive
    fresh = ServingEngine(model, params, num_slots=1, max_len=32)
    replica.begin_recovery(fresh)
    assert replica.state is ReplicaState.RECOVERING and not replica.placeable
    replica.complete_recovery()
    assert replica.state is ReplicaState.HEALTHY


def test_fleet_chaos_env_vars(monkeypatch):
    """The fleet faults arm from the environment like every other chaos leg,
    so an unmodified serve script can be drilled."""
    monkeypatch.setenv("ACCELERATE_CHAOS_REPLICA_KILL_STEP", "5")
    monkeypatch.setenv("ACCELERATE_CHAOS_REPLICA_KILL_INDEX", "1")
    monkeypatch.setenv("ACCELERATE_CHAOS_HEARTBEAT_LOSS_STEP", "7")
    plan = FaultPlan.from_env()
    assert plan is not None and plan.active
    assert plan.replica_kill(4) is None
    assert plan.replica_kill(5) == 1
    assert plan.heartbeat_loss(7) == 0
    assert plan.replica_stall(5) is None
    assert any(e["fault"] == "replica_kill" for e in plan.events)


def test_fleet_transient_classifier():
    """Replica loss and queue saturation re-home/back off; malformed
    requests fail fast."""
    assert is_fleet_transient(ReplicaLost("gone", replica_index=1))
    assert is_fleet_transient(QueueFull("full", queue_depth=4))
    assert not is_fleet_transient(ValueError("prompt too long"))


def test_fleet_rollup_merges_raw_samples():
    """Counters sum; percentiles merge over raw samples (a mean of p99s is
    not a p99)."""
    a, b = ServingStats(2), ServingStats(4)
    for t in (0.010, 0.011, 0.012):
        a.record_step(t, active=2, waiting=1)
    for t in (0.100, 0.110):
        b.record_step(t, active=1, waiting=0)
    a.record_finish(0.5)
    b.record_finish(1.5)
    a.record_submit(), b.record_submit()
    out = fleet_rollup([a, b])
    assert out["replicas"] == 2
    assert out["steps"] == 5
    assert out["num_slots"] == 6
    assert out["requests_completed"] == 2
    assert out["tokens_generated"] == 3 * 2 + 2 * 1
    # merged p99 sits in b's slow samples, far above a's own p99
    assert out["per_token_p99_ms"] > 50
    assert out["request_latency_p50_ms"] == pytest.approx(1000.0, rel=0.01)


# -- engine-side hooks the router builds on -----------------------------------


def test_engine_drain_and_snapshot(llama):
    """drain(): admission stops, queued payloads come back for re-homing,
    already-doomed queued requests terminate here instead of resurrecting
    elsewhere; snapshot_requests() is the non-destructive view."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    active = engine.submit(_prompts([4], seed=11)[0], max_new_tokens=3)
    queued = engine.submit(_prompts([5], seed=12)[0], max_new_tokens=3)
    doomed = engine.submit(_prompts([6], seed=13)[0], max_new_tokens=3)
    engine.step()  # `active` takes the slot
    engine.cancel(doomed)  # after the step: drain's own sweep must retire it

    snap = engine.snapshot_requests()
    assert {p["request_id"] for p in snap} == {active, queued}  # cancelled excluded
    queued_only = engine.snapshot_requests(include_active=False)
    assert {p["request_id"] for p in queued_only} == {queued}

    payloads, retired = engine.drain()
    assert engine.draining
    assert [p["request_id"] for p in payloads] == [queued]
    assert payloads[0]["max_new_tokens"] == 3
    assert [r.request_id for r in retired] == [doomed]
    assert retired[0].finish_reason == "cancelled"
    assert engine.stats.requests_rehomed == 1
    with pytest.raises(QueueFull, match="draining"):
        engine.submit(_prompts([3], seed=14)[0], max_new_tokens=2)
    # active slots finish normally
    results = engine.run()
    assert results[active].finish_reason == "length"
    engine.resume_admission()
    assert len(engine.generate_many([_prompts([3], seed=15)[0]], max_new_tokens=2)) == 1


def test_cancel_landing_mid_step_wins_over_same_step_retirement(llama):
    """The double-free regression: a cancel that lands DURING a step (server
    thread, router failover) on a request that would retire naturally that
    same step must produce exactly one terminal result, reason 'cancelled' —
    cancel()'s True is never contradicted, so an upstream holder releasing
    per-request bookkeeping on the ack can't free it twice."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    rid = engine.submit(_prompts([4], seed=16)[0], max_new_tokens=2)
    engine.step()  # admit + token 1; next step would retire on length

    # hook whichever decode program the engine's layout actually runs
    attr = "_paged_decode_program" if engine.paged else "_decode_program"
    real = getattr(engine, attr)
    acked = []

    def hooked():
        program = real()

        def wrapper(*args):
            out = program(*args)
            acked.append(engine.cancel(rid))  # lands after the sweep ran
            return out

        return wrapper

    setattr(engine, attr, hooked)
    results = {r.request_id: r for r in engine.step()}
    setattr(engine, attr, real)
    assert acked == [True]
    assert results[rid].finish_reason == "cancelled"
    assert engine.stats.requests_cancelled == 1
    # the slot was freed exactly once: a fresh request serves through it
    out = engine.generate_many([_prompts([3], seed=17)[0]], max_new_tokens=2)
    assert len(out) == 1


def test_mid_step_deadline_expiry_spends_no_extra_step(llama):
    """A deadline crossing during the decode retires the request that same
    step (partial output kept) instead of burning one more decode."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=1, max_len=32)
    rid = engine.submit(_prompts([4], seed=18)[0], max_new_tokens=8, deadline_s=1000.0)
    engine.step()
    # deadline passes mid-flight: next step's sweep ran at t0, decode
    # completes after the deadline — retire at the bottom loop
    engine.scheduler.slots[0].deadline_s = (
        time.perf_counter() - engine.scheduler.slots[0].submitted_at + 1e-4
    )
    results = {}
    while engine.busy:
        for r in engine.step():
            results[r.request_id] = r
    assert results[rid].finish_reason == "expired"
    assert 1 <= results[rid].generated.size < 8
    assert engine.stats.requests_expired == 1


# -- loadgen + fleet ----------------------------------------------------------


def test_offered_load_through_router_with_kill(llama):
    """The serve-bench/bench.py drill shape: offered load through a routed
    fleet while chaos kills a replica — exact accounting end to end."""
    plan = FaultPlan(replica_kill_step=4, replica_kill_index=1)
    router = _router(llama, fault_plan=plan, max_queue=16)
    prompts = _prompts([3, 5, 7, 4, 6, 3, 5, 4], seed=19)
    point = run_offered_load(router, prompts, max_new_tokens=5)
    assert point["offered_requests"] == 8
    assert point["requests_completed"] == 8  # all terminal despite the death
    assert point["replica_deaths"] == 1
    assert point["loadgen_sheds"] == point["loadgen_retries"]
    assert point["replicas"] == 2
    # router-level sheds (the caller-visible ones) are what the loadgen saw
    assert point["router_sheds"] == point["loadgen_sheds"]


# -- review regressions -------------------------------------------------------


def test_health_escalation_to_draining_rehomes_queue(llama):
    """The AUTOMATIC path into DRAINING (health machine escalating a sick
    replica) re-homes the queue exactly like operator drain_replica() —
    queued requests must not keep feeding the replica the router just
    judged too sick to place on."""
    policy = HealthPolicy(degrade_after=1, drain_after=2, recover_after=99)
    router = _router(llama, num_slots=1, max_queue=8, health=policy)
    prompts = _prompts([4, 5, 6, 4], seed=20)
    rids = [router.submit(p, max_new_tokens=4) for p in prompts]
    sick = router.replicas[0].engine
    assert sick.scheduler.waiting >= 1  # 2 placed per replica, 1 slot each
    sick.stats.record_watchdog_trip()
    router.step()  # observe → DEGRADED
    sick.stats.record_watchdog_trip()
    sick.stats.record_quarantine()
    queued_on_sick = sick.scheduler.waiting  # still queued behind the 1 slot
    assert queued_on_sick >= 1
    router.step()  # observe → DRAINING → queue re-homed
    assert router.replicas[0].state is ReplicaState.DRAINING
    assert sick.scheduler.waiting == 0
    assert len(router._pending) >= queued_on_sick  # pulled off the sick replica
    results = router.run()
    assert sorted(results) == sorted(rids)
    assert all(r.finish_reason == "length" for r in results.values())
    assert router.rehomed >= queued_on_sick  # ...and re-placed on the healthy one
    assert router.replicas[0].state is ReplicaState.DEAD
    assert router.replicas[0].death_reason == "drained"


def test_no_placeable_shed_is_counted_and_priced(llama):
    """When every replica is DRAINING, the shed looks exactly like the
    all-queues-full shed: counted in router_sheds and carrying a real
    retry_after_s hint (not None, which would make well-behaved clients
    hammer at their floor backoff)."""
    router = _router(llama, num_slots=1, max_queue=8)
    prompts = _prompts([4, 5], seed=21)
    rids = [router.submit(p, max_new_tokens=3) for p in prompts]
    router.step()  # one active slot per replica, so the drains stay DRAINING
    router.drain_replica(0)
    router.drain_replica(1)
    with pytest.raises(QueueFull) as exc_info:
        router.submit(prompts[0], max_new_tokens=3)
    assert exc_info.value.retry_after_s is not None
    assert exc_info.value.retry_after_s > 0
    assert router.router_sheds == 1
    results = router.run()
    assert sorted(results) == sorted(rids)


def test_generate_many_raises_on_non_completion(llama):
    """A failed/expired/cancelled request must raise out of generate_many,
    not come back as a fabricated prompt+EOS row indistinguishable from a
    genuine completion (or crash padding with eos_token_id=None)."""
    from accelerate_tpu.serving.engine import ServingResult, generation_row

    prompt = np.arange(3, dtype=np.int32)
    failed = ServingResult(
        request_id=7, prompt=prompt, generated=np.zeros((0,), np.int32),
        finish_reason="failed", ttft_s=None, latency_s=0.1,
    )
    with pytest.raises(RuntimeError, match="'failed'"):
        generation_row(prompt, failed, 4, None)
    done = ServingResult(
        request_id=8, prompt=prompt, generated=np.asarray([9, 5], np.int32),
        finish_reason="eos", ttft_s=0.0, latency_s=0.1,
    )
    np.testing.assert_array_equal(
        generation_row(prompt, done, 4, 5), [0, 1, 2, 9, 5, 5, 5]
    )


def test_chaos_fleet_faults_not_recorded_when_invalid():
    """A fault the router rejects (index out of range, replica already dead)
    must not land in the plan's ledger — a drill that fired nothing must
    not look armed."""
    plan = FaultPlan(replica_kill_step=5, replica_kill_index=3)
    assert plan.replica_kill(5, valid=lambda i: False) is None
    assert not plan.events
    assert plan.replica_kill(5, valid=lambda i: True) == 3
    assert [e["fault"] for e in plan.events] == ["replica_kill"]
