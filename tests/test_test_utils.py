"""The self-launched distributed payload must pass on the virtual mesh
(reference tests/test_multigpu.py: launcher + test_script subprocess), and the
profiler context must produce a trace."""

import os
import subprocess
import sys

import pytest


def test_distributed_payload_passes_on_virtual_mesh():
    from accelerate_tpu import test_utils

    script = os.path.join(os.path.dirname(test_utils.__file__), "scripts", "test_script.py")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    result = subprocess.run(
        [sys.executable, script], capture_output=True, text=True, timeout=420, env=env
    )
    assert result.returncode == 0, f"payload failed:\n{result.stdout}\n{result.stderr}"
    assert "All distributed correctness checks passed." in result.stdout


def test_profile_context_writes_trace(tmp_path):
    import jax

    from accelerate_tpu import Accelerator

    acc = Accelerator()
    with acc.profile(str(tmp_path / "trace")) as log_dir:
        (jax.numpy.ones((8, 8)) @ jax.numpy.ones((8, 8))).block_until_ready()
    found = [
        os.path.join(root, f)
        for root, _, files in os.walk(log_dir)
        for f in files
    ]
    assert found, "profiler produced no trace files"
