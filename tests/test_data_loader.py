"""Sampler-shard arithmetic and loader behavior (reference tests/test_data_loader.py)."""

import numpy as np
import pytest

from accelerate_tpu.data_loader import (
    BatchSampler,
    BatchSamplerShard,
    DataLoaderShard,
    IterableDatasetShard,
    SeedableRandomSampler,
    SequentialSampler,
    prepare_data_loader,
    skip_first_batches,
)
from accelerate_tpu.state import GradientState, PartialState


def make_batch_sampler(n, batch_size, drop_last=False):
    return BatchSampler(SequentialSampler(n), batch_size=batch_size, drop_last=drop_last)


def shards_for(n, batch_size, num_processes, split_batches=False, even_batches=True, drop_last=False):
    inner_bs = batch_size * (num_processes if split_batches else 1)
    return [
        list(
            BatchSamplerShard(
                make_batch_sampler(n, inner_bs, drop_last),
                num_processes=num_processes,
                process_index=p,
                split_batches=split_batches,
                even_batches=even_batches,
            )
        )
        for p in range(num_processes)
    ]


def test_round_robin_even_split():
    # 16 samples, batch 2, 2 procs: 8 batches round-robin -> 4 each
    shards = shards_for(16, 2, 2)
    assert shards[0] == [[0, 1], [4, 5], [8, 9], [12, 13]]
    assert shards[1] == [[2, 3], [6, 7], [10, 11], [14, 15]]


def test_round_robin_uneven_pads_from_start():
    # 10 samples, batch 2, 2 procs -> 5 batches; final window padded by cycling
    shards = shards_for(10, 2, 2)
    assert all(len(b) == 2 for shard in shards for b in shard)
    # same number of batches per process
    assert len(shards[0]) == len(shards[1])
    # all original indices appear
    seen = {i for shard in shards for b in shard for i in b}
    assert seen == set(range(10))


def test_round_robin_drop_last():
    shards = shards_for(10, 2, 2, even_batches=False, drop_last=True)
    assert len(shards[0]) == len(shards[1]) == 2
    seen = {i for shard in shards for b in shard for i in b}
    assert seen == set(range(8))


def test_split_batches_mode():
    shards = shards_for(16, 2, 2, split_batches=True)
    # inner batch size = 4, each proc takes its slice of every batch
    assert shards[0][0] == [0, 1]
    assert shards[1][0] == [2, 3]
    assert len(shards[0]) == 4


def test_split_batches_indivisible_raises():
    sampler = make_batch_sampler(16, 3)
    with pytest.raises(ValueError):
        BatchSamplerShard(sampler, num_processes=2, process_index=0, split_batches=True)


def test_iterable_dataset_shard():
    data = list(range(11))
    shards = [
        list(
            IterableDatasetShard(
                data, batch_size=2, num_processes=2, process_index=p, drop_last=False
            )
        )
        for p in range(2)
    ]
    # each buffer of 4 split 2/2; last partial buffer padded from the first
    assert len(shards[0]) == len(shards[1])
    combined = set(shards[0]) | set(shards[1])
    assert set(range(11)).issubset(combined)


def test_seedable_sampler_deterministic():
    s1 = SeedableRandomSampler(10, seed=7)
    s2 = SeedableRandomSampler(10, seed=7)
    s1.set_epoch(3)
    s2.set_epoch(3)
    assert list(s1) == list(s2)
    s2.set_epoch(4)
    assert list(s1) != list(s2)


class DictDataset:
    def __init__(self, n):
        self.x = np.arange(n, dtype=np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return {"x": self.x[i], "y": 2 * self.x[i]}


def test_dataloader_shard_global_arrays():
    loader = prepare_data_loader(DictDataset(32), batch_size=8)
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0]["x"].shape == (8,)
    # batch is a global sharded jax array over the 8-device mesh
    assert len(batches[0]["x"].sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(batches[0]["x"]), np.arange(8, dtype=np.float32))


def test_dataloader_end_of_dataloader_flag():
    gs = GradientState()
    loader = prepare_data_loader(DictDataset(16), batch_size=8)
    flags = []
    for _ in loader:
        flags.append(loader.end_of_dataloader)
    assert flags == [False, True]
    assert not gs.in_dataloader  # cleanly removed after epoch


def test_dataloader_remainder():
    loader = prepare_data_loader(DictDataset(20), batch_size=8)
    rems = []
    for _ in loader:
        rems.append(loader.remainder)
    assert rems[-1] == 20 % 8  # 4 real samples in last global batch


def test_skip_first_batches():
    loader = prepare_data_loader(DictDataset(32), batch_size=8)
    skipped = skip_first_batches(loader, 2)
    batches = list(skipped)
    assert len(batches) == 2
    np.testing.assert_array_equal(np.asarray(batches[0]["x"]), np.arange(16, 24, dtype=np.float32))


def test_shuffle_epochs_differ():
    loader = prepare_data_loader(DictDataset(32), batch_size=8, shuffle=True, seed=0)
    loader.set_epoch(0)
    e0 = [np.asarray(b["x"]) for b in loader]
    loader.set_epoch(1)
    e1 = [np.asarray(b["x"]) for b in loader]
    assert not all(np.array_equal(a, b) for a, b in zip(e0, e1))
    # all samples covered each epoch
    assert set(np.concatenate(e0).tolist()) == set(range(32))


def test_total_batch_size():
    loader = prepare_data_loader(DictDataset(32), batch_size=4)
    # single process: total == per-process
    assert loader.total_batch_size == 4


# -- async prefetch ----------------------------------------------------------


class _SlowDataset:
    """Collate cost simulated in __getitem__ (runs in the producer thread)."""

    def __init__(self, n=24, delay=0.01):
        import numpy as _np

        self.x = _np.arange(n, dtype=_np.float32)
        self.delay = delay

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        import time as _t

        _t.sleep(self.delay)
        return {"x": self.x[i]}


def test_prefetch_yields_identical_batches():
    from accelerate_tpu.data_loader import prepare_data_loader

    ds = _SlowDataset(n=24, delay=0.0)
    sync_batches = [np.asarray(b["x"]) for b in prepare_data_loader(ds, batch_size=4, prefetch=0)]
    async_batches = [np.asarray(b["x"]) for b in prepare_data_loader(ds, batch_size=4, prefetch=2)]
    assert len(sync_batches) == len(async_batches)
    for s, a in zip(sync_batches, async_batches):
        np.testing.assert_array_equal(s, a)


def test_prefetch_overlaps_step_time():
    """With prefetch, data production hides under a slow consumer step."""
    import time

    from accelerate_tpu.data_loader import prepare_data_loader

    per_item, batch, n = 0.004, 4, 32
    step_time = per_item * batch  # consumer exactly as slow as the producer

    def run(prefetch):
        loader = prepare_data_loader(_SlowDataset(n=n, delay=per_item), batch_size=batch, prefetch=prefetch)
        start = time.perf_counter()
        for _ in loader:
            time.sleep(step_time)
        return time.perf_counter() - start

    # best-of-2 per mode, interleaved, to ride out CI scheduling noise
    t_sync, t_async = run(0), run(2)
    t_sync = min(t_sync, run(0))
    t_async = min(t_async, run(2))
    # perfect overlap halves the wall time; demand at least 25%
    assert t_async < t_sync * 0.75, f"no overlap: async {t_async:.3f}s vs sync {t_sync:.3f}s"


def test_prefetch_propagates_dataset_errors():
    from accelerate_tpu.data_loader import prepare_data_loader

    class Broken:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i >= 4:
                raise RuntimeError("boom at item 4")
            return {"x": np.float32(i)}

    loader = prepare_data_loader(Broken(), batch_size=4, prefetch=2)
    with pytest.raises(RuntimeError, match="boom"):
        list(loader)


def test_prefetch_abandoned_iteration_cleans_up():
    import threading

    from accelerate_tpu.data_loader import prepare_data_loader

    loader = prepare_data_loader(_SlowDataset(n=32, delay=0.001), batch_size=4, prefetch=2)
    it = iter(loader)
    next(it)
    it.close()  # abandon mid-epoch
    # the producer thread must be joined in the generator's finally block
    leaked = [t for t in threading.enumerate() if t.name == "accelerate-tpu-prefetch" and t.is_alive()]
    assert not leaked, f"prefetch threads leaked: {leaked}"


def test_prefetch_end_of_dataloader_flag_timing():
    """The flag must flip only when the LAST batch is handed out, even though
    the producer finished reading the dataset batches earlier."""
    from accelerate_tpu.data_loader import prepare_data_loader

    loader = prepare_data_loader(_SlowDataset(n=12, delay=0.0), batch_size=4, prefetch=3)
    seen = []
    for batch in loader:
        seen.append(loader.end_of_dataloader)
    assert seen == [False, False, True]
