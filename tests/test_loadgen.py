"""Offered-load generation (serving/loadgen.py): arrival-trace helpers and
the loadgen's own client-observed ledger.

The trace makers are pinned on their statistical claims — burst amplitude
(the clump's inter-arrival gaps shrink by the multiplier), diurnal period
(arrival density follows the sinusoid's peak and trough halves), strict
monotonicity — and ``run_offered_load``'s ``arrival_times=`` escape hatch
is drilled end to end against a real engine: offered == completed, the
TTFT/latency percentiles come from the results the caller actually saw,
and the finish-reason histogram accounts for every completion.
"""

import numpy as np
import pytest

import jax

from accelerate_tpu.models import Llama
from accelerate_tpu.serving import (
    ServingEngine,
    make_burst_trace,
    make_diurnal_trace,
    run_offered_load,
)


@pytest.fixture(scope="module")
def llama():
    model = Llama("llama-tiny")
    return model, model.init(jax.random.key(0))


# -- trace shape --------------------------------------------------------------


def test_burst_trace_amplitude():
    """The middle burst_fraction of arrivals runs burst_multiplier× faster:
    the mean inter-arrival gap inside the clump is ~multiplier× smaller
    than outside (Poisson, so statistically — large n, loose tolerance)."""
    n, mult = 4000, 4.0
    times = make_burst_trace(n, base_rps=10.0, burst_multiplier=mult,
                             burst_fraction=0.5, seed=0)
    gaps = np.diff(np.asarray(times))
    lo, hi = n // 4, n - n // 4
    outside = np.concatenate([gaps[: lo - 1], gaps[hi:]])
    inside = gaps[lo:hi]
    ratio = outside.mean() / inside.mean()
    assert ratio == pytest.approx(mult, rel=0.25)


def test_burst_trace_monotone_and_positive():
    times = make_burst_trace(500, base_rps=50.0, seed=3)
    assert times[0] > 0.0
    assert all(b > a for a, b in zip(times, times[1:]))


def test_diurnal_trace_period():
    """Arrivals pile into the sinusoid's peak half-period and thin out in
    the trough half: folding every arrival by the period, the peak half
    must hold clearly more than the trough half."""
    period = 8.0
    times = make_diurnal_trace(4000, base_rps=50.0, period_s=period,
                               amplitude=0.8, seed=1)
    phase = np.asarray(times) % period
    peak = int((phase < period / 2).sum())  # sin > 0: rate above base
    trough = len(times) - peak
    assert peak > 2 * trough


def test_diurnal_trace_monotone():
    times = make_diurnal_trace(500, base_rps=50.0, amplitude=0.9, seed=2)
    assert all(b > a for a, b in zip(times, times[1:]))


def test_trace_validation():
    with pytest.raises(ValueError, match="n must be positive"):
        make_burst_trace(0, 10.0)
    with pytest.raises(ValueError, match="base_rps"):
        make_burst_trace(10, 0.0)
    with pytest.raises(ValueError, match="burst_multiplier"):
        make_burst_trace(10, 10.0, burst_multiplier=0.5)
    with pytest.raises(ValueError, match="burst_fraction"):
        make_burst_trace(10, 10.0, burst_fraction=1.5)
    with pytest.raises(ValueError, match="amplitude"):
        make_diurnal_trace(10, 10.0, amplitude=1.0)
    with pytest.raises(ValueError, match="period_s"):
        make_diurnal_trace(10, 10.0, period_s=0.0)


def test_traces_are_deterministic_per_seed():
    assert make_burst_trace(50, 10.0, seed=7) == make_burst_trace(50, 10.0, seed=7)
    assert make_burst_trace(50, 10.0, seed=7) != make_burst_trace(50, 10.0, seed=8)
    assert make_diurnal_trace(50, 10.0, seed=7) == make_diurnal_trace(50, 10.0, seed=7)


# -- run_offered_load ledger --------------------------------------------------


def test_arrival_times_validation(llama):
    model, params = llama
    engine = ServingEngine(model, params, num_slots=2, max_len=64)
    prompts = [np.arange(4, dtype=np.int32)] * 3
    with pytest.raises(ValueError, match="one arrival per prompt"):
        run_offered_load(engine, prompts, 4, arrival_times=[0.0, 0.1])
    with pytest.raises(ValueError, match="non-decreasing"):
        run_offered_load(engine, prompts, 4, arrival_times=[0.0, 0.2, 0.1])


def test_offered_load_ledger_with_arrival_times(llama):
    """The escape hatch end to end: an explicit arrival trace replays
    against a real engine; every offered request completes, the ledger's
    percentiles exist and order sanely, and the finish-reason histogram
    accounts for every completion."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=2, max_len=64)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 1024, (int(s),)).astype(np.int32)
               for s in rng.integers(4, 12, 8)]
    arrivals = make_burst_trace(len(prompts), base_rps=200.0, seed=0)
    point = run_offered_load(engine, prompts, 4, arrival_times=arrivals)
    assert point["offered_requests"] == len(prompts)
    assert point["requests_completed"] == len(prompts)
    assert point["offered_rps"] is None  # the trace, not a uniform rate
    assert point["loadgen_ttft_p50_ms"] > 0
    assert point["loadgen_ttft_p99_ms"] >= point["loadgen_ttft_p50_ms"]
    assert point["loadgen_latency_p50_ms"] >= point["loadgen_ttft_p50_ms"]
    assert point["loadgen_latency_p99_ms"] >= point["loadgen_latency_p50_ms"]
    assert sum(point["loadgen_finish_reasons"].values()) == len(prompts)
    assert point["loadgen_finish_reasons"] == {"length": len(prompts)}


def test_offered_load_uniform_rate_keeps_ledger(llama):
    """The pre-existing uniform-rate path reports the same ledger keys —
    one output schema whatever drove the arrivals."""
    model, params = llama
    engine = ServingEngine(model, params, num_slots=2, max_len=64)
    prompts = [np.arange(6, dtype=np.int32)] * 3
    point = run_offered_load(engine, prompts, 3)
    assert point["requests_completed"] == 3
    assert point["loadgen_ttft_p50_ms"] > 0
    assert point["loadgen_finish_reasons"] == {"length": 3}
