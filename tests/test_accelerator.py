"""End-to-end Accelerator tests: training parity, accumulation, clipping,
checkpoint round-trip (reference tests/test_accelerator.py + test_script.py)."""

import os
import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, GradientAccumulationPlugin, ParallelismConfig
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel


class ArrayDataset:
    def __init__(self, x, y):
        self.x, self.y = x, y

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return {"x": self.x[i], "y": self.y[i]}


def _make_data(n=64, seed=0):
    ds = RegressionDataset(length=n, seed=seed)
    return ArrayDataset(ds.x, ds.y)


class LinearModel:
    """Minimal model with init/apply protocol."""

    def init(self, rng):
        del rng
        return {"a": jnp.zeros((), jnp.float32), "b": jnp.zeros((), jnp.float32)}

    @staticmethod
    def apply(params, x):
        return params["a"] * x + params["b"]


def loss_fn(params, batch):
    pred = LinearModel.apply(params, batch["x"])
    return jnp.mean((pred - batch["y"]) ** 2)


def train(accelerator, epochs=3, lr=0.1, clip=None, batch_size=16):
    model, optimizer, loader = accelerator.prepare(
        LinearModel(), optax.sgd(lr), _make_data()
    )
    # loader got default batch size 8
    losses = []
    for epoch in range(epochs):
        loader.set_epoch(epoch)
        for batch in loader:
            with accelerator.accumulate(model):
                loss = accelerator.backward(loss_fn, batch)
                if clip:
                    accelerator.clip_grad_norm_(model, clip)
                optimizer.step()
                optimizer.zero_grad()
            losses.append(float(loss))
    return model, losses


def test_training_decreases_loss():
    accelerator = Accelerator()
    model, losses = train(accelerator)
    assert losses[-1] < losses[0] * 0.2
    # recovered approximately y = 2x + 3
    params = jax.device_get(model.params)
    assert abs(float(params["a"]) - 2.0) < 0.5
    assert abs(float(params["b"]) - 3.0) < 0.5


def test_training_parity_single_vs_mesh():
    """Distributed run must match the math of a plain single-device loop
    (reference test_script.py training parity)."""
    accelerator = Accelerator()
    model, _ = train(accelerator, epochs=2)
    dist_params = jax.device_get(model.params)

    # plain jax reference loop, same batches (sequential sampler, batch 8)
    data = _make_data()
    params = {"a": jnp.zeros(()), "b": jnp.zeros(())}
    tx = optax.sgd(0.1)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        g = jax.grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    for _ in range(2):
        for start in range(0, 64, 8):
            batch = {
                "x": jnp.asarray(data.x[start : start + 8]),
                "y": jnp.asarray(data.y[start : start + 8]),
            }
            params, opt_state = step(params, opt_state, batch)
    np.testing.assert_allclose(float(dist_params["a"]), float(params["a"]), rtol=1e-5)
    np.testing.assert_allclose(float(dist_params["b"]), float(params["b"]), rtol=1e-5)


def test_gradient_accumulation_equivalence():
    """accum=4 with lr applied at sync must equal large-batch steps."""
    accelerator = Accelerator(gradient_accumulation_steps=4)
    model, optimizer, loader = accelerator.prepare(LinearModel(), optax.sgd(0.1), _make_data())
    steps = 0
    for batch in loader:  # 8 batches of 8
        with accelerator.accumulate(model):
            accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        steps += 1
    assert optimizer.step_count == 2  # 8 batches / accum 4

    # reference: same data in 2 batches of 32
    data = _make_data()
    params = {"a": jnp.zeros(()), "b": jnp.zeros(())}
    tx = optax.sgd(0.1)
    opt_state = tx.init(params)
    for start in (0, 32):
        batch = {"x": jnp.asarray(data.x[start : start + 32]), "y": jnp.asarray(data.y[start : start + 32])}
        g = jax.grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(g, opt_state, params)
        params = optax.apply_updates(params, updates)
    got = jax.device_get(model.params)
    np.testing.assert_allclose(float(got["a"]), float(params["a"]), rtol=1e-5)
    np.testing.assert_allclose(float(got["b"]), float(params["b"]), rtol=1e-5)


def test_accumulation_respects_end_of_dataloader():
    """Partial final window still steps (sync_with_dataloader)."""
    accelerator = Accelerator(gradient_accumulation_steps=3)
    model, optimizer, loader = accelerator.prepare(LinearModel(), optax.sgd(0.1), _make_data())
    for batch in loader:  # 8 batches, 3-accum -> steps at 3, 6, and end (8)
        with accelerator.accumulate(model):
            accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
    assert optimizer.step_count == 3


def test_clip_grad_norm():
    accelerator = Accelerator()
    model, optimizer, loader = accelerator.prepare(LinearModel(), optax.sgd(0.01), _make_data())
    batch = next(iter(loader))
    accelerator.backward(loss_fn, batch)
    accelerator.clip_grad_norm_(model, 0.001)
    before = jax.device_get(model.params)
    optimizer.step()
    after = jax.device_get(model.params)
    # update magnitude bounded by lr * clip
    delta = abs(float(after["a"]) - float(before["a"])) + abs(float(after["b"]) - float(before["b"]))
    assert delta <= 0.01 * 0.001 * 2 + 1e-9


def test_clip_grad_value():
    accelerator = Accelerator()
    model, optimizer, loader = accelerator.prepare(LinearModel(), optax.sgd(0.01), _make_data())
    batch = next(iter(loader))
    accelerator.backward(loss_fn, batch)
    accelerator.clip_grad_value_(model, 0.002)
    before = jax.device_get(model.params)
    optimizer.step()
    after = jax.device_get(model.params)
    # each parameter's update magnitude bounded by lr * clip_value
    assert abs(float(after["a"]) - float(before["a"])) <= 0.01 * 0.002 + 1e-9
    assert abs(float(after["b"]) - float(before["b"])) <= 0.01 * 0.002 + 1e-9


def test_clip_grad_value_compiled_step():
    accelerator = Accelerator()
    model, optimizer, loader = accelerator.prepare(LinearModel(), optax.sgd(0.01), _make_data())
    step = accelerator.compiled_step(loss_fn, clip_grad_value=0.002)
    before = jax.device_get(model.params)
    step(next(iter(loader)))
    after = jax.device_get(model.params)
    assert abs(float(after["a"]) - float(before["a"])) <= 0.01 * 0.002 + 1e-9
    assert abs(float(after["b"]) - float(before["b"])) <= 0.01 * 0.002 + 1e-9


def test_fp16_loss_scaling_runs():
    accelerator = Accelerator(mixed_precision="fp16")
    model, optimizer, loader = accelerator.prepare(LinearModel(), optax.sgd(0.05), _make_data())
    for batch in loader:
        with accelerator.accumulate(model):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
    assert np.isfinite(float(loss))
    assert optimizer.scale is not None
    assert not optimizer.step_was_skipped


def test_bf16_policy_compute_dtype():
    accelerator = Accelerator(mixed_precision="bf16")

    captured = {}

    def probe_loss(params, batch):
        captured["param_dtype"] = params["a"].dtype
        captured["x_dtype"] = batch["x"].dtype
        pred = LinearModel.apply(params, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    model, optimizer, loader = accelerator.prepare(LinearModel(), optax.sgd(0.1), _make_data())
    batch = next(iter(loader))
    loss = accelerator.backward(probe_loss, batch)
    assert captured["param_dtype"] == jnp.bfloat16
    assert captured["x_dtype"] == jnp.bfloat16
    assert loss.dtype == jnp.float32
    # master params stay fp32
    assert model.params["a"].dtype == jnp.float32


def test_compiled_step_matches_eager():
    a1 = Accelerator()
    model, optimizer, loader = a1.prepare(LinearModel(), optax.sgd(0.1), _make_data())
    step = a1.compiled_step(loss_fn)
    for batch in loader:
        loss = step(batch)
    fused = jax.device_get(model.params)

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    a2 = Accelerator()
    model2, optimizer2, loader2 = a2.prepare(LinearModel(), optax.sgd(0.1), _make_data())
    for batch in loader2:
        with a2.accumulate(model2):
            a2.backward(loss_fn, batch)
            optimizer2.step()
            optimizer2.zero_grad()
    eager = jax.device_get(model2.params)
    np.testing.assert_allclose(float(fused["a"]), float(eager["a"]), rtol=1e-5)
    np.testing.assert_allclose(float(fused["b"]), float(eager["b"]), rtol=1e-5)


def test_gather_for_metrics_dedups_padding():
    accelerator = Accelerator()
    loader = accelerator.prepare(_make_data(n=20))  # batch 8 -> remainder 4
    seen = []
    for batch in loader:
        preds = batch["x"]
        gathered = accelerator.gather_for_metrics(preds)
        seen.append(np.asarray(gathered))
    total = np.concatenate(seen)
    assert total.shape[0] == 20  # no duplicated padded samples


def test_save_load_state_roundtrip(tmp_path):
    accelerator = Accelerator()
    model, optimizer, loader = accelerator.prepare(LinearModel(), optax.adam(0.1), _make_data())
    for batch in loader:
        with accelerator.accumulate(model):
            accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        break
    params_before = jax.device_get(model.params)
    opt_before = jax.device_get(jax.tree.leaves(optimizer.opt_state))
    accelerator.save_state(str(tmp_path / "ckpt"))

    # keep training, then restore
    for batch in loader:
        with accelerator.accumulate(model):
            accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
    accelerator.load_state(str(tmp_path / "ckpt"))
    params_after = jax.device_get(model.params)
    np.testing.assert_allclose(float(params_after["a"]), float(params_before["a"]))
    np.testing.assert_allclose(float(params_after["b"]), float(params_before["b"]))
    for a, b in zip(opt_before, jax.device_get(jax.tree.leaves(optimizer.opt_state))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_scheduler_steps_with_optimizer():
    accelerator = Accelerator(gradient_accumulation_steps=2)
    schedule = optax.linear_schedule(1.0, 0.0, 100)
    model, optimizer, loader, scheduler = accelerator.prepare(
        LinearModel(), optax.sgd(0.1), _make_data(), schedule
    )
    for batch in loader:
        with accelerator.accumulate(model):
            accelerator.backward(loss_fn, batch)
            optimizer.step()
            scheduler.step()
            optimizer.zero_grad()
    # 8 batches / accum 2 = 4 optimizer steps; with split_batches=False the
    # counter ticks once per data-parallel worker (reference scheduler.py:73-82)
    # and the default mesh puts all 8 devices on the data axis -> 4 * 8; the 4
    # accumulation micro-steps add one tick each (adjust_scheduler=True default,
    # reference scheduler.py:62-64).
    assert scheduler.step_count == 4 * 8 + 4
    assert scheduler.get_last_lr()[0] == pytest.approx(1.0 - 36 / 100)


def test_trigger_primitive():
    accelerator = Accelerator()
    assert not accelerator.check_trigger()
    accelerator.set_trigger()
    assert accelerator.check_trigger()
    assert not accelerator.check_trigger()  # reset after firing


def test_backward_without_optimizer_raises():
    """Grads with no optimizer prepared would be silently dropped — must raise."""
    accelerator = Accelerator()
    model = accelerator.prepare(LinearModel())
    batch = {"x": jnp.ones((8,)), "y": jnp.ones((8,))}
    with pytest.raises(ValueError, match="no optimizer"):
        accelerator.backward(loss_fn, batch)


def test_grad_fn_cache_holds_strong_refs_and_is_bounded():
    accelerator = Accelerator()
    model, optimizer, _ = accelerator.prepare(LinearModel(), optax.sgd(0.1), _make_data())
    batch = {"x": jnp.ones((8,)), "y": jnp.ones((8,))}
    limit = accelerator._GRAD_FN_CACHE_LIMIT
    for i in range(limit + 3):
        def fresh_loss(params, b, _i=i):  # distinct code object per iteration
            pred = LinearModel.apply(params, b["x"])
            return jnp.mean((pred - b["y"]) ** 2) + 0.0 * _i
        accelerator.backward(fresh_loss, batch)
    assert len(accelerator._grad_fns) <= limit
    # keys hold the loss_fn object itself (strong ref), not just its id
    assert all(callable(k[0]) for k in accelerator._grad_fns)


def test_compiled_step_fp16_applies_loss_scaling():
    """compiled_step must run GradScaler semantics: params move on finite steps
    and a synthetic overflow skips the update and backs off the scale.
    zero_stage=0 pins the LEGACY replicated program: its GSPMD backward
    all-reduces fp16 cotangents, whose deliberate early overflow this test's
    backoff expectations encode (the ZeRO path sums cotangents in f32 after
    unscale and holds a higher scale — covered in test_zero.py)."""
    accelerator = Accelerator(
        mixed_precision="fp16", parallelism=ParallelismConfig(zero_stage=0)
    )
    model, optimizer, _ = accelerator.prepare(LinearModel(), optax.sgd(0.1), _make_data())
    step = accelerator.compiled_step(loss_fn)
    init_scale = float(optimizer.scale)
    batch = {"x": jnp.linspace(-1, 1, 8), "y": 2 * jnp.linspace(-1, 1, 8) + 3}
    # the first steps overflow by design (the scaled cotangent exceeds fp16
    # max), backing the scale off until an update fits and applies
    for _ in range(5):
        loss0 = float(step(batch))
        assert np.isfinite(loss0)
        if float(jax.device_get(model.params)["b"]) != 0.0:
            break
    assert float(optimizer.scale) < init_scale  # backoff happened
    moved = jax.device_get(model.params)
    assert float(moved["b"]) != 0.0  # update applied once the scale fit
    scale_before = float(optimizer.scale)
    # overflow batch: inf target makes grads non-finite -> skip + backoff
    params_snapshot = jax.device_get(model.params)
    bad = {"x": jnp.ones((8,)), "y": jnp.full((8,), np.inf, jnp.float32)}
    step(bad)
    after = jax.device_get(model.params)
    np.testing.assert_allclose(float(after["a"]), float(params_snapshot["a"]))
    np.testing.assert_allclose(float(after["b"]), float(params_snapshot["b"]))
    assert float(optimizer.scale) < scale_before
    # the fused path must surface the skip so the scheduler doesn't tick
    assert optimizer.step_was_skipped
    step(batch)
    assert not optimizer.step_was_skipped


def test_compiled_step_fp16_matches_eager_path():
    """fp16 compiled_step and the backward()/step() path must produce the same
    parameters on finite data (both implement the same scaler semantics).
    Pinned on the legacy replicated program (zero_stage=0): both sides then
    share the same GSPMD backward, including where its fp16 cotangent
    collectives overflow. The ZeRO fused program keeps its fp16 backward
    collective-free (sums in f32 after unscale), so its scale trajectory is
    legitimately different — asserted in test_zero.py, not here."""
    a1 = Accelerator(
        mixed_precision="fp16", parallelism=ParallelismConfig(zero_stage=0)
    )
    model1, opt1, loader1 = a1.prepare(LinearModel(), optax.sgd(0.1), _make_data())
    step = a1.compiled_step(loss_fn)
    for batch in loader1:
        step(batch)
    fused = jax.device_get(model1.params)
    scale_fused = float(opt1.scale)

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()

    a2 = Accelerator(
        mixed_precision="fp16", parallelism=ParallelismConfig(zero_stage=0)
    )
    model2, opt2, loader2 = a2.prepare(LinearModel(), optax.sgd(0.1), _make_data())
    for batch in loader2:
        with a2.accumulate(model2):
            a2.backward(loss_fn, batch)
            opt2.step()
            opt2.zero_grad()
    eager = jax.device_get(model2.params)
    np.testing.assert_allclose(float(fused["a"]), float(eager["a"]), rtol=1e-4)
    np.testing.assert_allclose(float(fused["b"]), float(eager["b"]), rtol=1e-4)
    assert scale_fused == float(opt2.scale)


def test_scheduler_counter_scales_with_data_extent():
    """!split_batches compensation ticks by the data-parallel extent (batch
    shards), not the host count."""
    from accelerate_tpu.scheduler import AcceleratedScheduler

    accelerator = Accelerator(parallelism=ParallelismConfig(data=4, tensor=2))
    model, optimizer, _ = accelerator.prepare(LinearModel(), optax.sgd(0.1), _make_data())
    sched = AcceleratedScheduler(lambda c: 0.1 / (1 + c), optimizer=optimizer)
    accelerator.gradient_state._set_sync_gradients(True)
    batch = {"x": jnp.ones((8,)), "y": jnp.ones((8,))}
    accelerator.backward(loss_fn, batch)
    optimizer.step()
    sched.step()
    assert sched.step_count == 4  # data extent, tensor axis doesn't tick


def test_checkpoint_npz_fallback_roundtrip(tmp_path, monkeypatch):
    """save without safetensors writes .npz; load must find it."""
    import accelerate_tpu.checkpointing as ck

    flat = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    target = str(tmp_path / "model_0.safetensors")
    # simulate missing safetensors at save time
    import builtins
    real_import = builtins.__import__

    def no_safetensors(name, *args, **kwargs):
        if name.startswith("safetensors"):
            raise ImportError("simulated absence")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", no_safetensors)
    ck._save_flat(flat, target, safe_serialization=True)
    monkeypatch.setattr(builtins, "__import__", real_import)
    assert not os.path.exists(target)
    loaded = ck._load_flat(target)  # resolves the .npz sibling
    np.testing.assert_array_equal(loaded["w"], flat["w"])


def test_clip_settings_clearable():
    """Clipping registrations are sticky; explicit None clears them."""
    accelerator = Accelerator()
    model, optimizer, loader = accelerator.prepare(LinearModel(), optax.sgd(0.5), _make_data())
    accelerator.clip_grad_value_(1e-6)
    accelerator.clip_grad_norm_(1e-6)
    accelerator.clip_grad_value_(None)
    accelerator.clip_grad_norm_(None)
    batch = next(iter(loader))
    accelerator.backward(loss_fn, batch)
    before = jax.device_get(model.params)
    optimizer.step()
    after = jax.device_get(model.params)
    # with both clips cleared the update is NOT bounded by lr * 1e-6
    delta = abs(float(after["a"]) - float(before["a"]))
    assert delta > 0.5 * 1e-6 * 10


def test_prepare_rejects_loss_function():
    """A loss fn passed to prepare() must fail loudly, not become a scheduler
    (VERDICT r3 weak #7: silent AcceleratedScheduler wrap)."""
    import pytest

    accelerator = Accelerator()
    accelerator.prepare(LinearModel(), optax.sgd(0.1))

    def loss(params, batch):
        return 0.0

    with pytest.raises(TypeError, match="loss function"):
        accelerator.prepare(loss)


def test_prepare_still_accepts_schedules():
    accelerator = Accelerator()
    accelerator.prepare(LinearModel(), optax.sgd(0.1))
    sched = accelerator.prepare(optax.linear_schedule(1.0, 0.0, 100))
    from accelerate_tpu import AcceleratedScheduler

    assert isinstance(sched, AcceleratedScheduler)
