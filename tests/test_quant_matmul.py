"""Fused int8/int4 dequant-matmul (ops/quant_matmul.py): kernel vs the
dequantize-then-matmul reference, the QuantizedWeight pytree contract, and
the quantized-resident serving path that eliminates the bf16 shadow."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.ops.quant_matmul import quant_dot, quant_matmul
from accelerate_tpu.utils.quantization import (
    QuantizedWeight,
    dequantize_weight,
    quantize_weight,
)


def _quantized(rng, k, n, bits):
    w = rng.normal(size=(k, n)).astype(np.float32)
    q, scale = quantize_weight(w, bits=bits)
    return QuantizedWeight(jnp.asarray(q), jnp.asarray(scale), bits, jnp.float32)


@pytest.mark.parametrize("bits", [8, 4])
def test_fused_matmul_matches_dequant_reference(bits):
    rng = np.random.default_rng(bits)
    qw = _quantized(rng, 64, 48, bits)
    x = jnp.asarray(rng.normal(size=(2, 5, 64)).astype(np.float32))
    got = quant_matmul(x, qw)
    want = x @ dequantize_weight(qw.q, qw.scale, bits, jnp.float32)
    assert got.shape == (2, 5, 48)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=1e-5)


def test_fused_matmul_blocked_k_accumulation():
    """K larger than one block: the revisited-output accumulation over K
    blocks must equal the single contraction."""
    rng = np.random.default_rng(7)
    qw = _quantized(rng, 2048, 16, 8)  # 4 K-blocks at the 512 ceiling
    x = jnp.asarray(rng.normal(size=(3, 2048)).astype(np.float32) / 32.0)
    got = quant_matmul(x, qw)
    want = x @ qw.dequantize().astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_quant_dot_passthrough_for_plain_arrays():
    x = jnp.ones((2, 8))
    w = jnp.full((8, 3), 2.0)
    np.testing.assert_array_equal(np.asarray(quant_dot(x, w)), np.asarray(x @ w))


def test_quantized_weight_pytree_rides_scan_and_stack():
    """The packed container must survive jnp.stack via tree.map (the layer
    stacker) and lax.scan leading-axis slicing (the layer loop) with its
    bits/dtype aux intact."""
    rng = np.random.default_rng(0)
    layers = [_quantized(rng, 8, 6, 8) for _ in range(3)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *layers)
    assert isinstance(stacked, QuantizedWeight)
    assert stacked.q.shape == (3, 8, 6) and stacked.scale.shape == (3, 6)
    assert stacked.shape == (3, 8, 6)

    def body(carry, qw):
        return carry + quant_matmul(jnp.ones((1, 8)), qw).sum(), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), stacked)
    want = sum(float((jnp.ones((1, 8)) @ l.dequantize().astype(jnp.float32)).sum()) for l in layers)
    assert np.isclose(float(total), want, rtol=1e-5)


def test_int4_stacked_dequantize_doubles_contraction_axis():
    """A STACKED int4 leaf [L, K/2, N] (the layer-scan form) must
    dequantize to [L, K, N] with each layer's rows interleaved on axis -2 —
    not the layer axis — and match the per-layer dequant exactly."""
    rng = np.random.default_rng(2)
    layers = [_quantized(rng, 16, 6, 4) for _ in range(3)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *layers)
    deq = np.asarray(stacked.dequantize())
    assert deq.shape == (3, 16, 6) == stacked.shape
    for i, layer in enumerate(layers):
        np.testing.assert_array_equal(deq[i], np.asarray(layer.dequantize()))


def test_int4_logical_shape_and_contraction():
    rng = np.random.default_rng(1)
    qw = _quantized(rng, 32, 8, 4)
    assert qw.q.shape == (16, 8)  # two rows per stored byte
    assert qw.shape == (32, 8)
    out = quant_matmul(jnp.ones((1, 32), jnp.float32), qw)
    assert out.shape == (1, 8)


def test_quantized_resident_serving_eliminates_shadow():
    """from_streamed(use_kernels=True) on an int8 streamer keeps matrix
    weights PACKED (QuantizedWeight leaves), installs the fused dot hook,
    serves the same tokens as the shadowed reference at temperature 0, and
    the resident layer bytes drop by more than 2x (int8 + fp32 sidecar vs
    the fp32/bf16 shadow)."""
    from accelerate_tpu.big_modeling import dispatch_model, make_layered_device_map
    from accelerate_tpu.models import GPT2
    from accelerate_tpu.ops.quant_matmul import quant_dot as expected_hook
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.utils.quantization import QuantizationConfig

    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, model.config.vocab_size, (n,)).astype(np.int32)
               for n in (5, 19)]

    def streamed():
        return dispatch_model(
            model, jax.tree.map(jnp.array, params),
            make_layered_device_map(model, "cpu"), dtype=jnp.float32,
            quantization=QuantizationConfig(load_in_8bit=True),
        )

    def layer_bytes(engine):
        return sum(
            leaf.nbytes for leaf in jax.tree.leaves(
                engine.params["layers"], is_leaf=lambda x: isinstance(x, QuantizedWeight)
            )
        )

    try:
        ref_engine = ServingEngine.from_streamed(
            streamed(), num_slots=2, max_len=64, use_kernels=False
        )
        ref_rows = ref_engine.generate_many(prompts, max_new_tokens=6)
        assert model.dot_fn is None  # the shadowed path installs nothing

        eng = ServingEngine.from_streamed(
            streamed(), num_slots=2, max_len=64, use_kernels=True
        )
        assert model.dot_fn is expected_hook
        summary = eng.kernel_summary()
        assert summary["quant_matmul"] == "pallas"
        assert summary["quantized_weight_leaves"] > 0
        rows = eng.generate_many(prompts, max_new_tokens=6)
        assert all(np.array_equal(a, b) for a, b in zip(ref_rows, rows))
        assert layer_bytes(eng) * 2 < layer_bytes(ref_engine)
    finally:
        model.dot_fn = None  # detach: the module-scoped model may be shared
