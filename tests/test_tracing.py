"""Request-scoped distributed tracing + SLO burn-rate monitoring.

The observability acceptance drills (docs/observability.md, "Request
tracing"), all tier-1-fast on CPU: every offered request ends with exactly
one complete span tree whose terminal ``retired`` reason matches the
engine's ``finish_reason`` — under healthy traffic AND under chaos
(prefill-kill, handoff-loss); a request handed off between disaggregated
pools keeps ONE trace id with spans on both replicas; ``{"kind":
"resilience"}`` / handoff records gain a ``trace_id`` field without losing
any pre-existing key; the fleet rollup merges trace/SLO counters like the
handoff economy (sums + raw-sample percentiles, never a mean of p99s);
Perfetto export is loadable JSON; and tracing compiles nothing — the traced
decode/prefill programs gate clean against the untraced contracts.
"""

import json
import os

import numpy as np
import pytest

import jax

from accelerate_tpu.models import Llama
from accelerate_tpu.resilience import FaultPlan
from accelerate_tpu.serving import ServingEngine, ServingRouter, run_offered_load
from accelerate_tpu.serving.loadgen import make_mixed_prompts
from accelerate_tpu.telemetry import (
    RequestTracer,
    ServingStats,
    SLObjective,
    SLOMonitor,
    Telemetry,
    TelemetryConfig,
    default_objectives,
    fleet_rollup,
    to_perfetto,
    trace_summary,
)

TERMINAL = ("eos", "length", "expired", "cancelled", "failed")


@pytest.fixture(scope="module")
def llama():
    model = Llama("llama-tiny")
    return model, model.init(jax.random.key(0))


def _prompts(lengths, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (s,)).astype(np.int32) for s in lengths]


def _disagg(llama, tracer, roles=("prefill", "decode"), fault_plan=None,
            telemetry=None, **engine_kwargs):
    model, params = llama
    kwargs = {"num_slots": 2, "max_len": 64, **engine_kwargs}
    return ServingRouter(
        engine_factory=lambda: ServingEngine(model, params, **kwargs),
        num_replicas=len(roles),
        roles=list(roles),
        fault_plan=fault_plan,
        telemetry=telemetry,
        tracer=tracer,
    )


def _traces_by_request(tracer):
    by_rid = {}
    for record in tracer.completed:
        assert record["request_id"] not in by_rid, (
            f"request {record['request_id']} owns TWO span trees"
        )
        by_rid[record["request_id"]] = record
    return by_rid


def _assert_complete(record):
    """One complete span tree: every span closed, exactly one terminal
    ``retired`` whose reason is terminal, and the retire is the record's."""
    retired = [s for s in record["spans"] if s["kind"] == "retired"]
    assert len(retired) == 1
    assert retired[0]["reason"] == record["reason"]
    assert record["reason"] in TERMINAL
    for span in record["spans"]:
        assert span["t1"] is not None, f"orphan open span {span['name']}"
        assert span["t1"] >= span["t0"]


# -- the span tree, single engine ---------------------------------------------


def test_engine_trace_complete_span_tree(llama, tmp_path):
    """Every request gets one trace: queued → admitted → prefill[i] →
    decode (with first_token) → retired(reason); a long prompt's chunked
    prefill shows one span per chunk; traces flush as {"kind": "trace"}
    records; and tracing compiles NOTHING in steady state."""
    model, params = llama
    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    tracer = RequestTracer(telemetry=hub)
    engine = ServingEngine(
        model, params, num_slots=2, max_len=64, prefill_chunk=16, tracer=tracer,
        telemetry=hub,
    )
    engine.warmup()
    assert tracer.traces_completed == 0  # warmup's synthetic requests untraced
    compiles_before = engine.compiles.compile_count
    prompts = _prompts([3, 7, 40, 5])
    ids = [engine.submit(p, max_new_tokens=5) for p in prompts]
    results = engine.run()
    assert engine.compiles.compile_count == compiles_before  # tracing adds 0
    assert tracer.open_count == 0
    by_rid = _traces_by_request(tracer)
    assert sorted(by_rid) == sorted(ids)
    for rid in ids:
        record = by_rid[rid]
        _assert_complete(record)
        assert record["reason"] == results[rid].finish_reason
        kinds = [s["kind"] for s in record["spans"]]
        for expected in ("queued", "admitted", "prefill", "decode",
                         "first_token", "retired"):
            assert expected in kinds, (rid, kinds)
        assert record["ttft_s"] is not None and record["ttft_s"] > 0
        assert abs(record["ttft_s"] - results[rid].ttft_s) < 1e-6
    # the 40-token prompt chunked at 16: one prefill[i] span per chunk
    long_rid = ids[2]
    chunk_spans = [s for s in by_rid[long_rid]["spans"] if s["kind"] == "prefill"]
    assert len(chunk_spans) == 3
    assert [s["name"] for s in chunk_spans] == ["prefill[0]", "prefill[1]", "prefill[2]"]
    # span durations landed as raw samples for the rollup to merge
    assert len(engine.stats.span_seconds["decode"]) == len(prompts)
    assert engine.stats.traces_completed == len(prompts)
    # the jsonl sink holds the same trees
    lines = [
        json.loads(line)
        for line in open(tmp_path / "telemetry.jsonl")
        if line.strip()
    ]
    trace_records = [r for r in lines if r["kind"] == "trace"]
    assert sorted(r["request_id"] for r in trace_records) == sorted(ids)
    # the summary line names the top spans
    assert "decode" in trace_summary(by_rid[long_rid])


def test_trace_crosses_pools_single_trace_id(llama):
    """The disaggregation acceptance: a request prefilled on the prefill
    pool and decoded on the decode pool via live-KV handoff keeps ONE trace
    — parked + handoff_attempt(adopted) spans on the source, decode on the
    destination, one retired."""
    tracer = RequestTracer()
    router = _disagg(llama, tracer)
    prompts = _prompts([3, 7, 12, 5, 9, 4])
    router.generate_many(prompts, max_new_tokens=6)
    assert router.kv_handoffs == len(prompts)
    assert tracer.open_count == 0
    by_rid = _traces_by_request(tracer)
    assert len(by_rid) == len(prompts)
    for record in by_rid.values():
        _assert_complete(record)
        replicas = {s.get("replica") for s in record["spans"] if s.get("replica")}
        assert {"replica0", "replica1"} <= replicas, record["spans"]
        handoffs = [s for s in record["spans"] if s["kind"] == "handoff_attempt"]
        assert [s["outcome"] for s in handoffs] == ["adopted"]
        parked = [s for s in record["spans"] if s["kind"] == "parked"]
        assert len(parked) == 1 and parked[0]["outcome"] == "released"
        decode = [s for s in record["spans"] if s["kind"] == "decode"]
        assert decode and all(s["replica"] == "replica1" for s in decode)


# -- satellite: exact accounting under chaos ----------------------------------


def test_exact_accounting_under_prefill_kill(llama):
    """Chaos kills the prefill replica mid-stream (parked KV and all):
    every offered request still ends with exactly one complete span tree
    whose retired reason matches the engine's finish_reason, and no orphan
    spans survive the fleet drain."""
    tracer = RequestTracer()
    plan = FaultPlan(replica_kill_step=2, replica_kill_index=0)
    router = _disagg(llama, tracer, fault_plan=plan)
    prompts = make_mixed_prompts(
        6, 1024, 3, 8, long_fraction=0.2, long_multiplier=4, seed=3
    )
    rids = [router.submit(p, max_new_tokens=5) for p in prompts]
    results = []  # via step(), not run(): a dict would hide duplicates
    while router.busy:
        results.extend(router.step())
    assert router.replica_deaths == 1
    assert sorted(r.request_id for r in results) == sorted(rids)
    assert tracer.open_count == 0, "orphan span trees after fleet drain"
    by_rid = _traces_by_request(tracer)
    assert sorted(by_rid) == sorted(rids)
    requeued = 0
    for result in results:
        record = by_rid[result.request_id]
        _assert_complete(record)
        assert record["reason"] == result.finish_reason
        # a failover's re-opened queued span starts at the RE-submit, never
        # backdated to the original submitted_at — backdating would fold the
        # request's whole earlier life into queued[1] and double-count it
        queued = [s for s in record["spans"] if s["kind"] == "queued"]
        for earlier, later in zip(queued, queued[1:]):
            requeued += 1
            assert later["t0"] >= earlier["t1"], (
                f"re-opened queued span backdated: {queued}"
            )
    assert requeued >= 1, "the kill drill re-homed nothing — drill misfired"
    # every retired trace landed in SOME replica's books (router-made
    # terminals included), so the rollup's counters sum to the offered set
    assert sum(r.engine.stats.traces_completed for r in router.replicas) == len(rids)


def test_router_terminal_lands_in_replica_books(llama):
    """A router-made terminal (failover budget exhausted) must retire the
    trace INTO a replica's ServingStats — without a sink, exactly the failed
    requests would vanish from the fleet's trace/SLO counters and the
    rollup would report a clean fleet mid-drill."""
    model, params = llama
    tracer = RequestTracer()
    slo = SLOMonitor(default_objectives(ttft_s=60.0))
    tracer.slo = slo
    plan = FaultPlan(replica_kill_step=1, replica_kill_index=0)
    router = ServingRouter(
        engine_factory=lambda: ServingEngine(model, params, num_slots=2, max_len=64),
        num_replicas=2,
        fault_plan=plan,
        tracer=tracer,
        max_failovers=0,  # any orphan fails straight through _terminal
    )
    rids = [router.submit(p, max_new_tokens=5) for p in _prompts([3, 4, 5, 6])]
    results = []
    while router.busy:
        results.extend(router.step())
    failed = [r for r in results if r.finish_reason == "failed"]
    assert failed, "the kill orphaned nothing — drill misfired"
    assert tracer.open_count == 0
    assert sum(r.engine.stats.traces_completed for r in router.replicas) == len(rids)
    assert sum(r.engine.stats.slo_bad_events for r in router.replicas) >= len(failed)
    by_rid = _traces_by_request(tracer)
    for result in failed:
        record = by_rid[result.request_id]
        _assert_complete(record)
        # the retired span carries the last host's lane, not a phantom one
        retired = next(s for s in record["spans"] if s["kind"] == "retired")
        assert retired.get("replica") in ("replica0", "replica1")


def test_exact_accounting_under_handoff_loss_loadgen(llama):
    """The serve-bench drill shape under loadgen: chaos loses the first
    handoff transfer mid-flight; the retry ladder runs, every offered
    request terminates exactly once, and the trace stream accounts for all
    of them (no orphans, no duplicates)."""
    tracer = RequestTracer()
    plan = FaultPlan(seed=0, handoff_loss_at=(0,))
    router = _disagg(llama, tracer, fault_plan=plan, max_queue=16)
    prompts = _prompts([3, 5, 7, 4, 6, 3], seed=12)
    point = run_offered_load(router, prompts, max_new_tokens=5)
    assert point["offered_requests"] == 6
    assert point["requests_completed"] == 6
    assert tracer.open_count == 0
    by_rid = _traces_by_request(tracer)
    assert len(by_rid) == 6
    for record in by_rid.values():
        _assert_complete(record)
    # the lost attempt shows up as a non-adopted handoff outcome somewhere
    outcomes = [
        s["outcome"]
        for r in by_rid.values()
        for s in r["spans"]
        if s["kind"] == "handoff_attempt"
    ]
    assert "adopted" in outcomes
    assert any(o in ("retried", "fell_back") for o in outcomes)


# -- satellite: trace ids threaded into existing record kinds -----------------


def test_trace_id_threaded_into_resilience_and_handoff_records(llama, tmp_path):
    """{"kind": "resilience"} and the router's kv_handoff records carry the
    request's trace_id, and pre-existing schemas only GAIN the field."""
    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    tracer = RequestTracer(telemetry=hub)
    plan = FaultPlan(seed=0, handoff_loss_at=(0,))
    router = _disagg(llama, tracer, fault_plan=plan, telemetry=hub)
    prompts = _prompts([3, 7, 5])
    router.generate_many(prompts, max_new_tokens=4)
    lines = [
        json.loads(line)
        for line in open(tmp_path / "telemetry.jsonl")
        if line.strip()
    ]
    trace_ids = {r["trace_id"] for r in lines if r["kind"] == "trace"}
    assert len(trace_ids) == 3
    prefilled = [
        r for r in lines if r["kind"] == "resilience" and r.get("event") == "prefilled"
    ]
    assert prefilled
    for record in prefilled:
        # the pre-existing schema (PR 9), plus exactly the new field
        assert {"kind", "step", "time", "process_index", "engine", "event",
                "request_id", "pages"} <= set(record)
        assert record["trace_id"] in trace_ids
    handoffs = [
        r for r in lines if r["kind"] == "fleet" and r.get("event") == "kv_handoff"
    ]
    assert handoffs
    for record in handoffs:
        assert {"kind", "fleet_step", "event", "outcome", "request_id",
                "src"} <= set(record)
        assert record["trace_id"] in trace_ids
    adopted = [r for r in handoffs if r["outcome"] == "adopted"]
    assert adopted and {"dst", "pages", "bytes", "seconds", "attempts"} <= set(adopted[0])


def test_records_default_null_trace_id_without_tracer(llama, tmp_path):
    """Tracing off: the new field is present (schema is stable either way)
    but null — non-request records always read null too."""
    model, params = llama
    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    engine = ServingEngine(model, params, num_slots=1, max_len=32, telemetry=hub)
    engine.warmup()  # warmup itself queues one request per bucket
    engine.scheduler.max_queue = 1
    from accelerate_tpu.serving import QueueFull

    engine.submit(np.arange(1, 4, dtype=np.int32), max_new_tokens=2)
    with pytest.raises(QueueFull):  # 1 waiting >= max_queue: admission sheds
        engine.submit(np.arange(1, 4, dtype=np.int32), max_new_tokens=2)
    engine.run()
    lines = [
        json.loads(line)
        for line in open(tmp_path / "telemetry.jsonl")
        if line.strip()
    ]
    sheds = [r for r in lines if r["kind"] == "resilience" and r.get("event") == "shed"]
    assert sheds and all(r["trace_id"] is None for r in sheds)


# -- satellite: fleet rollup merges trace/SLO counters ------------------------


def test_fleet_rollup_merges_trace_and_slo_counters():
    """3-replica synthetic rollup: counters SUM; span-duration percentiles
    merge over the raw samples — the fleet p99 lands in the slow replica's
    tail, NOT at the mean of per-replica p99s."""
    a, b, c = (ServingStats(2) for _ in range(3))
    for _ in range(9):
        a.record_span("decode", 0.010)
    b.record_span("decode", 0.500)  # one slow outlier on one replica
    a.record_span("queued", 0.001)
    c.record_span("queued", 0.002)
    for stats, good, bad in ((a, 5, 1), (b, 3, 0), (c, 2, 2)):
        for _ in range(good):
            stats.record_slo_event(True)
        for _ in range(bad):
            stats.record_slo_event(False)
    a.record_trace_completed()
    a.record_trace_completed()
    b.record_trace_completed()
    out = fleet_rollup([a, b, c], roles=["prefill", "decode", "decode"])
    assert out["traces_completed"] == 3
    assert out["trace_spans"] == 9 + 1 + 1 + 1
    assert out["slo_good_events"] == 10
    assert out["slo_bad_events"] == 3
    assert out["slo_bad_rate"] == round(3 / 13, 6)
    # raw-sample merge: the p99 of [0.01]*9 + [0.5] interpolates into the
    # outlier (~456ms), while a mean of per-replica p99s ((10 + 500) / 2)
    # would sit near 255ms — the two disagree by ~200ms on 10 samples
    assert out["span_decode_p99_ms"] > 400
    assert out["span_decode_p50_ms"] == 10.0
    assert out["span_queued_p99_ms"] >= 1.9
    # snapshots carry the same keys (diffable column-for-column)
    snap = ServingStats(2).snapshot()
    for key in ("traces_completed", "trace_spans", "slo_good_events",
                "slo_bad_events"):
        assert snap[key] == 0


# -- the SLO monitor ----------------------------------------------------------


def _trace(reason="length", ttft=0.1, latency=1.0, outcomes=()):
    return {
        "trace_id": "tr-test", "request_id": 1, "reason": reason,
        "ttft_s": ttft, "latency_s": latency,
        "spans": [{"kind": "handoff_attempt", "outcome": o} for o in outcomes],
    }


def test_slo_monitor_burn_rate_math():
    """burn_rate = bad_rate / (1 - target): 10% bad against a 99% target
    burns 10x the budget (breached); exactly-at-budget is NOT a breach."""
    monitor = SLOMonitor(
        [SLObjective("ttft", "ttft", threshold_s=0.5, target=0.9, window_s=60.0)]
    )
    for i in range(9):
        monitor.observe(_trace(ttft=0.1), stamp=float(i))
    monitor.observe(_trace(ttft=2.0), stamp=9.0)  # 1/10 bad, budget 0.1
    (record,) = monitor.evaluate(stamp=10.0)
    assert record["window_observed"] == 10 and record["window_bad"] == 1
    assert record["bad_rate"] == 0.1
    assert record["burn_rate"] == 1.0  # burning exactly the budget
    assert not record["breached"]
    monitor.observe(_trace(ttft=3.0), stamp=10.5)
    (record,) = monitor.evaluate(stamp=11.0)
    assert record["burn_rate"] > 1.0 and record["breached"]
    assert monitor.breaches["ttft"] == 1
    # rolling window: past the horizon the old samples fall out
    (record,) = monitor.evaluate(stamp=1000.0)
    assert record["window_observed"] == 0 and record["burn_rate"] is None


def test_slo_classifiers_and_validation():
    err = SLObjective("errors", "error_rate", target=0.99)
    assert err.is_good(_trace(reason="length"))
    assert err.is_good(_trace(reason="cancelled"))  # the client's choice
    assert not err.is_good(_trace(reason="failed"))
    assert not err.is_good(_trace(reason="expired"))
    fb = SLObjective("fb", "handoff_fallback_rate", target=0.95)
    assert fb.is_good(_trace(outcomes=("adopted",)))
    assert fb.is_good(_trace(outcomes=("retried", "adopted")))
    assert not fb.is_good(_trace(outcomes=("retried", "fell_back")))
    ttft = SLObjective("t", "ttft", threshold_s=1.0)
    assert not ttft.is_good(_trace(ttft=None))  # no first token ever = bad
    with pytest.raises(ValueError, match="unknown SLO metric"):
        SLObjective("x", "p99_vibes")
    with pytest.raises(ValueError, match="threshold_s"):
        SLObjective("x", "ttft")
    with pytest.raises(ValueError, match="target"):
        SLObjective("x", "error_rate", target=1.0)
    with pytest.raises(ValueError, match="duplicate"):
        SLOMonitor([err, SLObjective("errors", "error_rate")])
    # per-replica counters land on the stats sink the rollup sums
    stats = ServingStats(2)
    monitor = SLOMonitor(default_objectives(ttft_s=1.0))
    monitor.observe(_trace(ttft=0.1), stats=stats)
    assert stats.slo_good_events == 3 and stats.slo_bad_events == 0
    monitor.observe(_trace(reason="failed", ttft=5.0), stats=stats)
    assert stats.slo_bad_events == 2  # ttft AND error objectives


# -- Perfetto export + CLI ----------------------------------------------------


def test_perfetto_export_chaos_drilled_disagg(llama, tmp_path, capsys):
    """The acceptance artifact: a chaos-drilled disagg run exports
    Perfetto-loadable JSON via `accelerate-tpu trace`, and a handed-off
    request's spans cross both pools under one trace id."""
    from accelerate_tpu.commands.cli import main

    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    tracer = RequestTracer(telemetry=hub)
    # lose the SECOND transfer attempt: attempt 0 adopts (a guaranteed
    # cross-pool handoff), attempt 1 exercises the retry ladder mid-drill
    plan = FaultPlan(seed=0, handoff_loss_at=(1,))
    router = _disagg(llama, tracer, fault_plan=plan, telemetry=hub)
    prompts = _prompts([3, 7, 12, 5])
    router.generate_many(prompts, max_new_tokens=5)
    assert tracer.open_count == 0

    out = tmp_path / "trace.json"
    rc = main(["trace", str(tmp_path), "--out", str(out), "--summary"])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "ui.perfetto.dev" in printed and "slowest" in printed
    payload = json.loads(out.read_text())
    events = payload["traceEvents"]
    assert events and payload["displayTimeUnit"] == "ms"
    # one process lane per replica, named
    lanes = {
        e["args"]["name"]: e["pid"] for e in events if e["name"] == "process_name"
    }
    assert {"replica0", "replica1"} <= set(lanes)
    # a handed-off request: spans in BOTH pools' lanes under one trace id
    by_trace: dict = {}
    for e in events:
        tid = e.get("args", {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, set()).add(e["pid"])
    crossing = [t for t, pids in by_trace.items() if len(pids) >= 2]
    assert crossing, "no trace crossed the pools"
    # adopted handoff attempts are visible by name
    assert any(e["name"] == "handoff_attempt[0](adopted)" for e in events)
    assert any(e["name"].startswith("retired(") for e in events)

    # filters compose; an id that matches nothing exits 1
    assert main(["trace", str(tmp_path), "--out", str(out),
                 "--trace-id", crossing[0]]) == 0
    assert main(["trace", str(tmp_path), "--out", str(out),
                 "--trace-id", "tr-nope"]) == 1


def test_serve_bench_trace_flag(llama, tmp_path, capsys, monkeypatch):
    """serve-bench --trace: the drill line prints the slowest request's
    span breakdown, SLO burn rates print, and the Perfetto JSON +
    telemetry.jsonl land in --trace-dir."""
    from accelerate_tpu.commands.cli import main

    monkeypatch.chdir(tmp_path)
    rc = main([
        "serve-bench", "--model", "llama-tiny", "--num-slots", "2",
        "--max-len", "64", "--requests", "4", "--max-new-tokens", "4",
        "--prompt-len-min", "3", "--prompt-len-max", "8",
        "--prefill-replicas", "1", "--decode-replicas", "1",
        "--chaos", "prefill-kill", "--chaos-step", "3",
        "--trace", "--trace-dir", str(tmp_path),
    ])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "slowest drill trace" in printed
    assert "slo ttft: burn rate" in printed
    assert "0 open (must be 0)" in printed
    # the sweep's per-point compile accounting survives tracing: the hub
    # attaches AFTER engine construction, so each point keeps its OWN
    # CompileTracker and the steady-state count stays 0 (a constructor-passed
    # hub would hand every engine the hub's process-lifetime tracker and
    # report warmup's compiles as steady-state)
    assert ", 0 after (steady state must be 0" in printed
    payload = json.loads((tmp_path / "trace.json").read_text())
    assert payload["traceEvents"]
    assert (tmp_path / "telemetry.jsonl").exists()


# -- contract gate: tracing adds zero device-program drift --------------------


def test_traced_programs_match_untraced_contracts(llama):
    """The traced engine's decode/prefill/adopt programs gate clean against
    the SAME checked-in contracts the untraced engine recorded — tracing is
    host-side stamps only, so in contract terms the programs are identical
    (collectives, donation, memory, schedule all unchanged)."""
    from accelerate_tpu.analysis.contracts import (
        default_contracts_dir,
        drift_count,
        gate_reports,
    )

    model, params = llama
    engine = ServingEngine(
        model, params, num_slots=2, max_len=64, page_size=16, prefill_chunk=16,
        tracer=RequestTracer(),
    )
    report = engine.analyze(compile=True, write_record=False)
    findings = gate_reports([report], default_contracts_dir())
    assert drift_count(findings) == 0, [str(f) for f in findings]
    assert not report.errors, [str(f) for f in report.errors]
