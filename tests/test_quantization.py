"""int8/int4 weight-only quantization (reference utils/bnb.py:44,
tests/test_quantization.py): quantize/dequantize bounds, packed streaming
dispatch parity, memory halving, and the load_and_quantize_model entry."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.big_modeling import QuantizedLayerPacker, dispatch_model
from accelerate_tpu.checkpointing import save_model_weights
from accelerate_tpu.models import Llama
from accelerate_tpu.utils.quantization import (
    QuantizationConfig,
    dequantize_weight,
    quantize_weight,
)


def test_quantize_roundtrip_int8():
    w = np.random.default_rng(0).normal(size=(64, 32)).astype(np.float32)
    q, scale = quantize_weight(w, bits=8)
    assert q.dtype == np.int8 and scale.shape == (32,)
    back = np.asarray(dequantize_weight(jnp.asarray(q), jnp.asarray(scale), 8, jnp.float32))
    # symmetric per-channel int8: error bounded by scale/2 per element
    assert np.abs(back - w).max() <= (scale.max() / 2) + 1e-6


def test_quantize_roundtrip_int4_packs_two_per_byte():
    w = np.random.default_rng(1).normal(size=(64, 32)).astype(np.float32)
    q, scale = quantize_weight(w, bits=4)
    assert q.shape == (32, 32)  # nibble-packed on the leading axis
    back = np.asarray(dequantize_weight(jnp.asarray(q), jnp.asarray(scale), 4, jnp.float32))
    assert back.shape == w.shape
    assert np.abs(back - w).max() <= (scale.max() / 2) + 1e-6


def test_int4_nibble_sign_extension_all_values():
    """Every representable int4 value round-trips the nibble packing exactly:
    pack all (low, high) pairs over [-7, 7] by hand, and ``unpack_int4``'s
    arithmetic-shift sign extension must reproduce them — negatives included
    — interleaved as rows 2i (low) / 2i+1 (high). This pins the shift
    semantics at utils/quantization.py directly against an integer
    reference instead of through a statistical round-trip."""
    from accelerate_tpu.utils.quantization import unpack_int4

    values = np.arange(-7, 8, dtype=np.int8)  # the symmetric-quantizer range
    low, high = np.meshgrid(values, values, indexing="ij")
    low, high = low.ravel(), high.ravel()
    packed = ((low & 0x0F) | ((high & 0x0F) << 4)).astype(np.int8)[:, None]
    unpacked = np.asarray(unpack_int4(jnp.asarray(packed)))
    assert unpacked.dtype == np.int8
    np.testing.assert_array_equal(unpacked[0::2, 0], low)
    np.testing.assert_array_equal(unpacked[1::2, 0], high)


def test_int4_dequantize_matches_float_reference():
    """dequantize_weight(bits=4) against a pure-numpy reference of the same
    spec: unpack both nibbles with sign, multiply by the per-channel scale —
    exact equality, not tolerance (the device path must not add rounding)."""
    rng = np.random.default_rng(5)
    w = rng.normal(size=(16, 6)).astype(np.float32)
    q, scale = quantize_weight(w, bits=4)
    got = np.asarray(dequantize_weight(jnp.asarray(q), jnp.asarray(scale), 4, jnp.float32))

    # numpy reference: low nibble rows 2i, high nibble rows 2i+1, sign-extended
    low = (q.astype(np.int8) << 4).astype(np.int8) >> 4
    high = q.astype(np.int8) >> 4
    vals = np.empty((q.shape[0] * 2,) + q.shape[1:], np.int8)
    vals[0::2], vals[1::2] = low, high
    want = vals.astype(np.float32) * scale.astype(np.float32)
    np.testing.assert_array_equal(got, want)
    # and the reference itself is a faithful quantization of w
    assert np.abs(want - w).max() <= (scale.max() / 2) + 1e-6


def test_int4_odd_leading_dim_rejected():
    with pytest.raises(ValueError, match="even leading dim"):
        quantize_weight(np.ones((3, 4), np.float32), bits=4)


def test_config_validation():
    with pytest.raises(ValueError):
        QuantizationConfig()
    with pytest.raises(ValueError):
        QuantizationConfig(load_in_8bit=True, load_in_4bit=True)
    assert QuantizationConfig(load_in_8bit=True).bits == 8
    assert QuantizationConfig(load_in_4bit=True).bits == 4


@pytest.fixture(scope="module")
def tiny():
    model = Llama("llama-tiny")
    params = jax.device_get(model.init(jax.random.key(0)))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (2, 12)), jnp.int32)
    full = model.apply(jax.tree.map(jnp.asarray, params), ids)
    return model, params, ids, full


def test_quantized_dispatch_close_to_full(tiny):
    model, params, ids, full = tiny
    cfg = model.config
    dm = {"embed_tokens": "device", "final_norm": "device", "lm_head": "device"}
    dm.update({f"layers.{i}": "cpu" for i in range(cfg.num_layers)})
    lm = dispatch_model(
        model, params, dm, dtype=jnp.float32, quantization=QuantizationConfig(load_in_8bit=True)
    )
    got = lm(ids)
    # int8 weights: logits close but not exact
    rel = np.abs(np.asarray(got) - np.asarray(full)).max() / np.abs(np.asarray(full)).max()
    assert rel < 0.05
    assert not np.array_equal(np.asarray(got), np.asarray(full))
    # top-1 predictions overwhelmingly preserved (random-init logits are
    # near-uniform, so a few positions may legitimately flip)
    agree = (np.argmax(np.asarray(got), -1) == np.argmax(np.asarray(full), -1)).mean()
    assert agree >= 0.9


def test_quantized_buffers_halve_memory(tiny):
    model, params, ids, _ = tiny
    cfg = model.config
    dm = {"embed_tokens": "device", "final_norm": "device", "lm_head": "device"}
    dm.update({f"layers.{i}": "cpu" for i in range(cfg.num_layers)})
    full = dispatch_model(model, params, dm, dtype=jnp.bfloat16)
    q8 = dispatch_model(model, params, dm, dtype=jnp.bfloat16, quantization=QuantizationConfig(load_in_8bit=True))
    q4 = dispatch_model(model, params, dm, dtype=jnp.bfloat16, quantization=QuantizationConfig(load_in_4bit=True))

    def layer_bytes(lm):
        buf = lm.layer_buffers[0]
        parts = buf if isinstance(buf, tuple) else (buf,)
        return sum(np.asarray(p).nbytes for p in parts)

    assert layer_bytes(q8) < layer_bytes(full) * 0.62  # int8 + fp32 sidecar < bf16
    assert layer_bytes(q4) < layer_bytes(q8) * 0.62


def test_quantized_generate_runs(tiny):
    model, params, ids, _ = tiny
    from accelerate_tpu import load_and_quantize_model

    lm = load_and_quantize_model(
        model, QuantizationConfig(load_in_8bit=True), params=params, device_map="auto", dtype=jnp.float32
    )
    out = lm.generate(ids[:1, :4], max_new_tokens=4)
    assert out.shape == (1, 8)


def test_load_and_quantize_from_checkpoint(tmp_path, tiny):
    model, params, ids, full = tiny
    from accelerate_tpu import load_and_quantize_model

    save_model_weights(params, str(tmp_path))
    lm = load_and_quantize_model(
        model, QuantizationConfig(load_in_8bit=True), weights_location=str(tmp_path),
        device_map="auto", dtype=jnp.float32,
    )
    got = lm(ids)
    rel = np.abs(np.asarray(got) - np.asarray(full)).max() / np.abs(np.asarray(full)).max()
    assert rel < 0.05


def test_quantized_disk_offload(tmp_path, tiny):
    model, params, ids, full = tiny
    cfg = model.config
    dm = {"embed_tokens": "device", "final_norm": "device", "lm_head": "device"}
    dm.update({f"layers.{i}": "disk" for i in range(cfg.num_layers)})
    lm = dispatch_model(
        model, params, dm, offload_dir=str(tmp_path), dtype=jnp.float32,
        quantization=QuantizationConfig(load_in_8bit=True),
    )
    got = lm(ids)
    rel = np.abs(np.asarray(got) - np.asarray(full)).max() / np.abs(np.asarray(full)).max()
    assert rel < 0.05
    import os

    assert any(f.endswith(".dat") for f in os.listdir(tmp_path))
