"""ZeRO-style sharded weight update (parallel/zero.py; ISSUE 12).

The claims this file pins, each as a measured property rather than prose:

- **Exactness** — the sharded update is the replicated update: identical
  seeded gradients through both paths give bit-identical params + optimizer
  state at float tolerance 0 over 10 steps (params + opt state gathered),
  on both the bert-tiny DP layout and a mixed data×fsdp llama layout.
- **Fidelity** — the fused ZeRO step's loss matches the plain (non-donated)
  GSPMD forward, which is the value that matches the float64 reference; the
  legacy donated FSDP program deviates from it on this backend.
- **Resilience** — a chaos-injected NaN step under guards skips the update
  bit-exactly and training continues (skip/restore semantics survive
  sharding); the fp16 scaler backs off on a genuine overflow and skips.
- **State** — checkpoint save→resume of the sharded optimizer state is
  bit-exact, including resharding onto a different mesh layout.
- **Caching** — the optimizer's update-program cache keys on the sharding
  layout, so a re-prepared optimizer on a different layout can never reuse
  a wrong-donation / wrong-shard program.
- **The audit has teeth** — optimizer state resolving to replication under
  declared ZeRO intent is an ERROR from the replication audit, and the
  schedule pass's ready-window classification behaves as documented.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu import (
    Accelerator,
    FullyShardedDataParallelPlugin,
    ParallelismConfig,
)
from accelerate_tpu.models import Bert, Llama
from accelerate_tpu.parallel.sharding import fold_update_spec, zero_batch_axes
from accelerate_tpu.parallel.zero import zero_eligible, zero_update_state_bytes
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils.random import set_seed

from jax.sharding import NamedSharding, PartitionSpec as P


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _bert_batch(model, n=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    acc_state = AcceleratorState()
    sharding = acc_state.data_sharding()
    return {
        "input_ids": jax.device_put(
            jnp.asarray(rng.integers(0, model.config.vocab_size, (n, seq)), jnp.int32),
            sharding,
        ),
        "attention_mask": jax.device_put(jnp.ones((n, seq), jnp.int32), sharding),
        "labels": jax.device_put(
            jnp.asarray(rng.integers(0, 2, (n,)), jnp.int32), sharding
        ),
    }


def _llama_loss(model):
    def loss_fn(params, batch):
        logits = model.apply(params, batch["input_ids"])[:, :-1].astype(jnp.float32)
        tgt = batch["input_ids"][:, 1:]
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt_logit = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
        return (lse - tgt_logit).mean()

    return loss_fn


def _tree_equal(a, b) -> bool:
    return all(jax.tree.leaves(jax.tree.map(np.array_equal, a, b)))


# ---------------------------------------------------------------------------
# enablement / spec engine
# ---------------------------------------------------------------------------


def test_zero_resolution_default_optout_and_demand():
    _reset()
    acc = Accelerator()
    assert acc._zero_update_sharding  # auto-on for plain data parallel
    _reset()
    acc = Accelerator(parallelism=ParallelismConfig(zero_stage=0))
    assert not acc._zero_update_sharding  # explicit legacy opt-out
    _reset()
    # model-parallel axes make the mesh ineligible: auto stays off...
    acc = Accelerator(parallelism=ParallelismConfig(data=4, tensor=2))
    assert not acc._zero_update_sharding
    _reset()
    # ...and demanding it fails loudly instead of silently degrading
    with pytest.raises(ValueError, match="zero_stage"):
        Accelerator(parallelism=ParallelismConfig(data=4, tensor=2, zero_stage=1))
    _reset()
    # legacy stage-1/2 FSDP keeps its explicit params-replicated contract
    acc = Accelerator(fsdp_plugin=FullyShardedDataParallelPlugin(stage=2))
    assert not acc._zero_update_sharding


def test_fold_update_spec_engine():
    _reset()
    mesh = AcceleratorState().mesh
    axes = zero_batch_axes(mesh)
    assert axes  # the 8-device test mesh has a data axis
    # largest divisible free dim takes the fold
    folded = fold_update_spec((64, 4), P(None, None), mesh, axes)
    assert folded[0] == (axes[0] if len(axes) == 1 else tuple(axes))
    assert folded[1] is None
    # an already-sharded dim is extended, preserving the outer split
    folded = fold_update_spec((64, 4), P("tensor", None), mesh, ("data",))
    assert folded[0] == ("tensor", "data")
    # nothing divisible: the spec survives untouched (replicated update leaf)
    assert fold_update_spec((3,), P(None), mesh, ("data",)) == P(None)
    # axes already present are never folded twice
    assert fold_update_spec((64,), P("data"), mesh, ("data",)) == P("data")


def test_zero_collective_layout_round_trip():
    """device_put storage layout and the manual all_gather/psum_scatter pair
    must agree on the axis linearization — including a tuple split over two
    mesh axes (the data×fsdp fold)."""
    from functools import partial

    from jax.experimental.shard_map import shard_map

    _reset()
    acc = Accelerator(parallelism=ParallelismConfig(data=2, fsdp=4))
    mesh = acc.mesh
    x = jnp.arange(64 * 3, dtype=jnp.float32).reshape(64, 3)
    spec = P(("data", "fsdp"), None)
    stored = jax.device_put(x, NamedSharding(mesh, spec))

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=spec,
        out_specs=P(),
        check_rep=False,
    )
    def gather(shard):
        full = jax.lax.all_gather(shard, ("data", "fsdp"), axis=0, tiled=True)
        return full

    out = np.asarray(jax.jit(gather)(stored))
    np.testing.assert_array_equal(out, np.asarray(x))

    @partial(
        shard_map, mesh=mesh, in_specs=P(), out_specs=spec, check_rep=False
    )
    def scatter(full):
        return jax.lax.psum_scatter(
            full, ("data", "fsdp"), scatter_dimension=0, tiled=True
        )

    # full replicated input: scatter sums 8 identical copies → 8x shards,
    # laid out exactly like the storage split
    scattered = jax.jit(scatter)(jax.device_put(x, NamedSharding(mesh, P())))
    np.testing.assert_array_equal(np.asarray(scattered), 8 * np.asarray(x))


def test_sharded_global_norm_counts_partially_folded_leaves_once():
    """A leaf whose dim divides by fsdp but not fsdp×data keeps only the
    fsdp split — its elements are REPLICATED across data, and the norm's
    uniform psum must not count them data-times (regression: gnorm inflation
    would over-clip vs the replicated path)."""
    from functools import partial

    from jax.experimental.shard_map import shard_map

    from accelerate_tpu.parallel.zero import sharded_global_norm

    _reset()
    acc = Accelerator(parallelism=ParallelismConfig(data=2, fsdp=4))
    mesh = acc.mesh
    rng = np.random.default_rng(0)
    full = {
        "folded": jnp.asarray(rng.standard_normal((16, 4)), jnp.float32),
        "partial": jnp.asarray(rng.standard_normal((12, 4)), jnp.float32),
        "replicated": jnp.asarray(rng.standard_normal((3,)), jnp.float32),
    }
    specs = {
        "folded": P(("data", "fsdp"), None),
        "partial": P("fsdp", None),  # 12 % 8 != 0: the data axis didn't fold
        "replicated": P(None),
    }
    stored = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k])) for k, v in full.items()
    }

    @partial(shard_map, mesh=mesh, in_specs=(specs,), out_specs=P(), check_rep=False)
    def norm(tree):
        return sharded_global_norm(tree, specs, ("data", "fsdp"), mesh)

    got = float(jax.jit(norm)(stored))
    want = float(np.sqrt(sum(np.sum(np.square(np.asarray(v))) for v in full.values())))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_leaf_coupling_optimizers_are_rejected_under_zero():
    """A transform that couples gradient leaves (clip_by_global_norm inside
    the chain) would compute its reduction over the local 1/N shard — the
    prepare-time probe must reject it with both fixes named, while plain
    adam-family transforms pass."""
    from accelerate_tpu.parallel.zero import tx_couples_across_leaves

    _reset()
    accelerator = Accelerator()
    model = Bert("bert-tiny")
    prepared = accelerator.prepare_model(model)
    coupled = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(1e-3))
    assert tx_couples_across_leaves(coupled, prepared.params)
    # within-leaf reductions (trust ratios, RMS clipping) are coupling too
    assert tx_couples_across_leaves(optax.adafactor(1e-3), prepared.params)
    assert tx_couples_across_leaves(optax.lamb(1e-3), prepared.params)
    assert not tx_couples_across_leaves(optax.adamw(1e-3), prepared.params)
    assert not tx_couples_across_leaves(optax.sgd(1e-2, momentum=0.9), prepared.params)
    with pytest.raises(ValueError, match="clip_grad_norm_|zero_stage=0"):
        accelerator.prepare_optimizer(coupled)
    # the legacy path still accepts it
    _reset()
    accelerator = Accelerator(parallelism=ParallelismConfig(zero_stage=0))
    accelerator.prepare_model(Bert("bert-tiny"))
    accelerator.prepare_optimizer(coupled)


def test_zero_update_state_bytes_formula():
    opt_chip, grad_chip = zero_update_state_bytes(1000, 4, 8)
    assert opt_chip == -(-1000 * 12 // 8)
    assert grad_chip == 500
    full_opt, full_grad = zero_update_state_bytes(1000, 4, 1)
    assert (full_opt, full_grad) == (12000, 4000)


# ---------------------------------------------------------------------------
# the bit-exactness gate (acceptance)
# ---------------------------------------------------------------------------


def _updated_state(make_acc, model_ctor, n_steps=10, lr=3e-4):
    """Feed IDENTICAL seeded gradients through the update path of the given
    accelerator config; return (params, opt_state) gathered to host."""
    _reset()
    set_seed(0)
    accelerator = make_acc()
    model = model_ctor()
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(lr))
    rng = np.random.default_rng(0)
    host_params = jax.tree.map(np.asarray, prepared.params)
    for _ in range(n_steps):
        grads = jax.tree.map(
            lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32),
            host_params,
        )
        optimizer.accumulate_grads(jax.device_put(grads, prepared.params_shardings))
        optimizer.step()
    return (
        jax.tree.map(np.asarray, prepared.params),
        jax.tree.map(np.asarray, optimizer.opt_state),
    )


def test_sharded_update_bit_equals_replicated_bert():
    """10 steps of identical gradients: the ZeRO-sharded adamw (1/N state)
    and the replicated adamw produce bit-identical params AND optimizer
    state at tolerance 0 — the decomposition is exact, not approximate."""
    p_z, o_z = _updated_state(lambda: Accelerator(), lambda: Bert("bert-tiny"))
    p_r, o_r = _updated_state(
        lambda: Accelerator(parallelism=ParallelismConfig(zero_stage=0)),
        lambda: Bert("bert-tiny"),
    )
    assert _tree_equal(p_z, p_r)
    assert _tree_equal(o_z, o_r)


def test_sharded_update_bit_equals_replicated_llama_mixed_mesh():
    """Same gate on a data×fsdp mesh with stage-3 FSDP: the fold extends the
    fsdp split with the data axis (tuple specs), and the update must still
    be bit-identical to the zero_stage=0 layout."""

    def make(stage):
        return lambda: Accelerator(
            parallelism=ParallelismConfig(data=2, fsdp=4, zero_stage=stage),
            fsdp_plugin=FullyShardedDataParallelPlugin(stage=3),
        )

    p_z, o_z = _updated_state(make(None), lambda: Llama("llama-tiny"))
    p_r, o_r = _updated_state(make(0), lambda: Llama("llama-tiny"))
    assert _tree_equal(p_z, p_r)
    assert _tree_equal(o_z, o_r)


def test_fused_zero_step_loss_matches_unpartitioned_forward():
    """The fused ZeRO FSDP step computes the same loss as the plain
    (non-donated, loss-only) GSPMD program — the value that agrees with the
    float64 reference. The legacy donated fused program deviates from it on
    this backend (~4e-3 relative), which is exactly why the manual program
    carries this anchor."""
    _reset()
    set_seed(0)
    accelerator = Accelerator(
        parallelism=ParallelismConfig(data=1, fsdp=jax.device_count()),
        fsdp_plugin=FullyShardedDataParallelPlugin(stage=3),
    )
    model = Llama("llama-tiny")
    prepared = accelerator.prepare_model(model)
    accelerator.prepare_optimizer(optax.adamw(3e-4))
    loss_fn = _llama_loss(model)
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": jax.device_put(
            jnp.asarray(rng.integers(0, model.config.vocab_size, (8, 32)), jnp.int32),
            accelerator.state.data_sharding(),
        )
    }
    reference = float(jax.jit(loss_fn)(prepared.params, batch))
    step = accelerator.compiled_step(loss_fn)
    fused = float(step(batch))
    np.testing.assert_allclose(fused, reference, rtol=1e-6)


def test_fused_zero_step_tracks_eager_path():
    """Fused ZeRO step vs the eager backward()/step() path over 5 steps on
    bert-tiny: same semantics, different tracing (manual vs auto-partitioned
    backward), so agreement is reassociation-level, not bitwise."""
    def run(fused: bool):
        _reset()
        set_seed(0)
        accelerator = Accelerator()
        model = Bert("bert-tiny")
        prepared = accelerator.prepare_model(model)
        optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
        batch = _bert_batch(model)
        loss_fn = Bert.loss_fn(model)
        if fused:
            step = accelerator.compiled_step(loss_fn)
            losses = [float(step(batch)) for _ in range(5)]
        else:
            losses = []
            for _ in range(5):
                accelerator.gradient_state._set_sync_gradients(True)
                losses.append(float(accelerator.backward(loss_fn, batch)))
                optimizer.step()
                optimizer.zero_grad()
        return losses, jax.tree.map(np.asarray, prepared.params)

    fused_losses, fused_params = run(True)
    eager_losses, eager_params = run(False)
    # the LOSS trajectory is the functional check: step k's loss is computed
    # on k-times-updated params, so agreement here means the param
    # trajectories are equivalent. Element-wise param comparison is NOT
    # meaningful between differently-traced backwards: bert-tiny's grads on
    # random labels sit at noise level, where adamw's m/sqrt(v) is
    # sign-sensitive to last-bit gradient differences.
    np.testing.assert_allclose(fused_losses, eager_losses, rtol=1e-4)


def test_zero_microbatch_accumulation_matches_legacy():
    """The in-program lax.scan over microbatches composes with the manual
    region (params gathered ONCE outside the scan — the gather cost
    amortizes over the window, unlike the replicated path's per-micro
    all-reduce), and its loss trajectory matches the legacy replicated
    program's."""

    def run(stage):
        _reset()
        set_seed(0)
        accelerator = Accelerator(
            gradient_accumulation_steps=2,
            parallelism=ParallelismConfig(zero_stage=stage),
        )
        model = Bert("bert-tiny")
        accelerator.prepare_model(model)
        accelerator.prepare_optimizer(optax.adamw(1e-3))
        batch = _bert_batch(model, n=16)
        step = accelerator.compiled_step(Bert.loss_fn(model))
        return [float(step(batch)) for _ in range(4)]

    zero_losses = run(None)
    legacy_losses = run(0)
    assert all(np.isfinite(zero_losses))
    np.testing.assert_allclose(zero_losses, legacy_losses, rtol=1e-4)


# ---------------------------------------------------------------------------
# resilience under sharding
# ---------------------------------------------------------------------------


def test_zero_guard_skip_survives_sharding():
    """A chaos-injected NaN step under the ZeRO fused program must skip the
    update bit-exactly: 5 guarded steps with NaN at step 2 end at EXACTLY
    the params of a fault-free 4-step ZeRO run."""
    from accelerate_tpu.resilience import FaultPlan, GuardPolicy, ResilienceConfig

    def clean(n_steps):
        _reset()
        set_seed(0)
        accelerator = Accelerator()
        model = Bert("bert-tiny")
        prepared = accelerator.prepare_model(model)
        accelerator.prepare_optimizer(optax.adamw(1e-3))
        step = accelerator.compiled_step(Bert.loss_fn(model))
        batch = _bert_batch(model)
        for _ in range(n_steps):
            step(batch)
        return jax.tree.map(np.asarray, prepared.params)

    clean_params = clean(4)
    _reset()
    set_seed(0)
    accelerator = Accelerator(
        resilience_config=ResilienceConfig(
            guard=GuardPolicy(check_every=100), fault_plan=FaultPlan(nan_steps=(2,))
        )
    )
    assert accelerator._zero_update_sharding
    model = Bert("bert-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    step = accelerator.compiled_step(Bert.loss_fn(model))
    batch = _bert_batch(model)
    losses = [float(step(batch)) for _ in range(5)]
    guard = accelerator.resilience.guard
    guard.check(prepared, optimizer)  # flush the window so the counter is live
    assert guard.skipped_steps == 1
    # chaos steps are 1-based: step 2 is the SECOND call; its skip means the
    # third loss (computed on the un-updated params) repeats the second
    assert losses[2] == losses[1]
    assert _tree_equal(clean_params, jax.tree.map(np.asarray, prepared.params))


def test_zero_fp16_scaler_semantics():
    """GradScaler under the ZeRO fused program: finite steps update, an
    injected-inf batch skips and backs off the scale, recovery resumes. The
    manual backward keeps its fp16 region collective-free, so the scale
    trajectory can sit HIGHER than the legacy GSPMD program's (whose fp16
    cotangent all-reduce overflows spuriously) — asserted semantics only."""

    class LinearModel:
        def init(self, rng):
            del rng
            return {"a": jnp.zeros((), jnp.float32), "b": jnp.zeros((), jnp.float32)}

        @staticmethod
        def apply(params, x):
            return params["a"] * x + params["b"]

    def loss_fn(params, batch):
        return jnp.mean((LinearModel.apply(params, batch["x"]) - batch["y"]) ** 2)

    _reset()
    accelerator = Accelerator(mixed_precision="fp16")
    assert accelerator._zero_update_sharding
    model, optimizer = accelerator.prepare(LinearModel(), optax.sgd(0.1))
    step = accelerator.compiled_step(loss_fn)
    sharding = accelerator.state.data_sharding()
    batch = {
        "x": jax.device_put(jnp.linspace(-1, 1, 8), sharding),
        "y": jax.device_put(2 * jnp.linspace(-1, 1, 8) + 3, sharding),
    }
    for _ in range(3):
        loss = float(step(batch))
        assert np.isfinite(loss)
    assert float(jax.device_get(model.params)["b"]) != 0.0
    scale_before = float(optimizer.scale)
    snapshot = jax.device_get(model.params)
    bad = {
        "x": jax.device_put(jnp.ones((8,)), sharding),
        "y": jax.device_put(jnp.full((8,), np.inf, jnp.float32), sharding),
    }
    step(bad)
    assert optimizer.step_was_skipped
    after = jax.device_get(model.params)
    np.testing.assert_array_equal(float(after["a"]), float(snapshot["a"]))
    np.testing.assert_array_equal(float(after["b"]), float(snapshot["b"]))
    assert float(optimizer.scale) < scale_before
    step(batch)
    assert not optimizer.step_was_skipped


# ---------------------------------------------------------------------------
# checkpointing: sharded state round-trip + resharding
# ---------------------------------------------------------------------------


def test_zero_checkpoint_roundtrip_bit_exact_and_reshards(tmp_path):
    """save_state → load_state of the ZeRO-sharded optimizer state is
    bit-exact across resume (same config), and loads correctly into a
    DIFFERENT mesh layout (replica-count change: data=8 → data=2×fsdp=4)."""

    def build(parallelism=None):
        _reset()
        set_seed(0)
        accelerator = Accelerator(parallelism=parallelism)
        model = Bert("bert-tiny")
        prepared = accelerator.prepare_model(model)
        optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
        step = accelerator.compiled_step(Bert.loss_fn(model))
        batch = _bert_batch(model)
        return accelerator, prepared, optimizer, step, batch

    # continuous 5-step reference
    _, prepared, optimizer, step, batch = build()
    for _ in range(5):
        step(batch)
    reference_params = jax.tree.map(np.asarray, prepared.params)
    reference_opt = jax.tree.map(np.asarray, optimizer.opt_state)

    # 3 steps → save → fresh accelerator → load → 2 more steps
    accelerator, prepared, optimizer, step, batch = build()
    for _ in range(3):
        step(batch)
    accelerator.save_state(str(tmp_path / "ckpt"))

    accelerator, prepared, optimizer, step, batch = build()
    accelerator.load_state(str(tmp_path / "ckpt"))
    for _ in range(2):
        step(batch)
    assert _tree_equal(reference_params, jax.tree.map(np.asarray, prepared.params))
    assert _tree_equal(reference_opt, jax.tree.map(np.asarray, optimizer.opt_state))

    # resharding: the same checkpoint restores onto a 2x4 mesh, where the
    # fold produces tuple splits — gathered values must match the saved ones
    accelerator, prepared, optimizer, step, batch = build(
        parallelism=ParallelismConfig(data=2, fsdp=4)
    )
    accelerator.load_state(str(tmp_path / "ckpt"))
    # the 3-step state we saved, gathered from the new layout
    _, prepared3, optimizer3, step3, batch3 = build()
    for _ in range(3):
        step3(batch3)
    assert _tree_equal(
        jax.tree.map(np.asarray, prepared3.params),
        jax.tree.map(np.asarray, prepared.params),
    )
    assert _tree_equal(
        jax.tree.map(np.asarray, optimizer3.opt_state),
        jax.tree.map(np.asarray, optimizer.opt_state),
    )


# ---------------------------------------------------------------------------
# the update-program cache keys on the sharding layout (satellite)
# ---------------------------------------------------------------------------


def test_update_program_cache_keyed_by_sharding_spec():
    """An optimizer whose state layout changes (re-prepared model / ZeRO
    layout swapped in) must trace a FRESH update program: reusing the old
    one would run with wrong donation aliases and wrong shard shapes. The
    clip settings stay part of the key alongside (regression for the
    original clip-keyed invalidation)."""
    _reset()
    acc = Accelerator()
    model = Bert("bert-tiny")
    prepared = acc.prepare_model(model)
    optimizer = acc.prepare_optimizer(optax.adamw(1e-3))
    rng = np.random.default_rng(0)
    grads = jax.device_put(
        jax.tree.map(
            lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32),
            jax.tree.map(np.asarray, prepared.params),
        ),
        prepared.params_shardings,
    )
    optimizer.accumulate_grads(grads)
    optimizer.step()
    assert len(optimizer._update_fns) == 1
    key_zero = next(iter(optimizer._update_fns))

    # clip change → new entry, old retained (flipping back is a cache hit)
    optimizer.set_clip_grad_norm(1.0)
    optimizer.accumulate_grads(grads)
    optimizer.step()
    assert len(optimizer._update_fns) == 2

    # layout change → new entry even at identical clip settings
    optimizer.set_clip_grad_norm(None)
    from accelerate_tpu.parallel.sharding import replicated

    rep = replicated(acc.mesh)
    optimizer._params_shardings = jax.tree.map(lambda _: rep, prepared.params_shardings)
    optimizer._opt_state_shardings = jax.tree.map(
        lambda _: rep, optimizer._opt_state_shardings
    )
    optimizer._opt_state_device_shardings = optimizer._opt_state_shardings
    optimizer.opt_state = jax.device_put(optimizer.opt_state, optimizer._opt_state_shardings)
    prepared.box.value = jax.device_put(prepared.box.value, jax.tree.map(lambda _: rep, prepared.params_shardings))
    optimizer.accumulate_grads(jax.device_put(grads, jax.tree.map(lambda _: rep, prepared.params_shardings)))
    optimizer.step()
    assert len(optimizer._update_fns) == 3
    assert optimizer._update_key() != key_zero
    # and the sharded program is the audited one: donation held on it
    report = optimizer.verify_donation()
    assert report.errors == [], report.render()


# ---------------------------------------------------------------------------
# the audit has teeth (acceptance: replication ERROR under declared intent)
# ---------------------------------------------------------------------------


def test_replicated_opt_state_under_zero_intent_is_an_error():
    """Seeded regression: the canonical bert program with its state forced
    back to full replication (the exact shape of "the update silently
    stopped sharding") must FAIL the replication audit with
    REPLICATED_PARAM errors — under declared ZeRO intent the audit asserts
    sharding, it does not inventory it."""
    from accelerate_tpu.parallel.sharding import replicated

    _reset()
    accelerator = Accelerator()
    assert accelerator._sharding_intent()  # ZeRO declares intent
    model = Bert("bert-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    rep = replicated(accelerator.mesh)
    prepared.params_shardings = jax.tree.map(lambda _: rep, prepared.params_shardings)
    prepared.box.value = jax.device_put(prepared.box.value, prepared.params_shardings)
    optimizer._params_shardings = prepared.params_shardings
    optimizer._opt_state_shardings = jax.tree.map(
        lambda _: rep, optimizer._opt_state_shardings
    )
    optimizer._opt_state_device_shardings = optimizer._opt_state_shardings
    optimizer.opt_state = jax.device_put(optimizer.opt_state, optimizer._opt_state_shardings)
    step = accelerator.compiled_step(Bert.loss_fn(model))
    batch = _bert_batch(model)
    report = accelerator.analyze(
        step=step,
        batch=batch,
        label="bert_tiny_step_seeded_replicated_opt",
        write_record=False,
        replication_threshold_bytes=1 << 14,
    )
    replicated_errors = [f for f in report.errors if f.code == "REPLICATED_PARAM"]
    assert replicated_errors, report.render()
    # both the moments and the parameters are named, so the author is
    # pointed at the state that lost its sharding
    flagged = " ".join(f.path for f in replicated_errors)
    assert "opt_state" in flagged or "mu" in flagged or "nu" in flagged, flagged


def test_schedule_ready_window_classification():
    """The sync-collective ready-window walk: a gather over program inputs
    whose consumer sits past independent compute is overlapped; a collective
    produced late and consumed immediately is serialized; an unscheduled
    module never credits sync overlap."""
    from accelerate_tpu.analysis.schedule import collective_schedule

    hlo = """
HloModule m, is_scheduled=true

ENTRY %main {
  %p0 = f32[16,16] parameter(0)
  %p1 = f32[128,16] parameter(1)
  %ag = f32[128,16] all-gather(f32[16,16] %p0), dimensions={0}
  %mm1 = f32[128,16] multiply(f32[128,16] %p1, f32[128,16] %p1)
  %mm2 = f32[128,16] add(f32[128,16] %mm1, f32[128,16] %p1)
  %use = f32[128,16] add(f32[128,16] %ag, f32[128,16] %mm2)
  %rs = f32[16,16] reduce-scatter(f32[128,16] %use), dimensions={0}
  ROOT %out = f32[16,16] negate(f32[16,16] %rs)
}
"""
    summary = collective_schedule(hlo)
    by_kind = {op["kind"]: op for op in summary["collectives"]}
    # the gather: ready at t=0 (parameter input), consumer after 2 compute
    assert by_kind["all_gather"]["overlapped"]
    assert by_kind["all_gather"]["overlap_compute_ops"] == 2
    # the scatter: produced by its own last dep (%use) right before, consumed
    # immediately by the ROOT — empty ready-window, serialized
    assert not by_kind["reduce_scatter"]["overlapped"]
    assert summary["sync_overlapped_count"] == 1
    assert summary["overlapped_count"] == 1

    unscheduled = collective_schedule(hlo.replace(", is_scheduled=true", ""))
    assert unscheduled["overlapped_count"] == 0


# ---------------------------------------------------------------------------
# telemetry + CLI surfaces
# ---------------------------------------------------------------------------


def test_state_bytes_per_chip_reports_shard_residency():
    from accelerate_tpu.telemetry.memory import state_bytes_per_chip

    _reset()
    acc = Accelerator()
    mesh = acc.mesh
    full = jnp.zeros((64, 8), jnp.float32)
    replicated_tree = {"m": jax.device_put(full, NamedSharding(mesh, P()))}
    sharded_tree = {"m": jax.device_put(full, NamedSharding(mesh, P("data")))}
    assert state_bytes_per_chip(replicated_tree) == full.nbytes
    assert state_bytes_per_chip(sharded_tree) == full.nbytes // 8


def test_estimate_memory_zero_column(capsys):
    from accelerate_tpu.commands.cli import main

    rc = main(["estimate-memory", "llama-tiny", "--replicas", "8"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "+adam/chip @8 (ZeRO)" in out
    assert "sharded 1/8 per chip" in out
    # and the column prices below the replicated train budget
    rc = main(["estimate-memory", "params=1000000", "--replicas", "8"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "+adam/chip @8 (ZeRO)" in out
