"""Paged decode-attention kernel (ops/paged_attention.py) and its serving
integration: the kernel path must be invisible at temperature 0 — same
tokens as the gather-reference decode over mixed lengths for BOTH decode
protocols — while never materializing the gathered view, keeping the
zero-steady-state-recompile invariant, and reporting its coverage in
telemetry."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import GPT2, Llama
from accelerate_tpu.ops.paged_attention import (
    _reference,
    paged_decode_attention,
    paged_kernel_fallback_reason,
)
from accelerate_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def llama():
    model = Llama("llama-tiny")
    return model, model.init(jax.random.key(0))


@pytest.fixture(scope="module")
def gpt2():
    model = GPT2("gpt2-tiny")
    return model, model.init(jax.random.key(0))


def _mixed_prompts(vocab, lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, (n,)).astype(np.int32) for n in lengths]


# ---------------------------------------------------------------------------
# op level
# ---------------------------------------------------------------------------


def test_kernel_matches_gather_reference_op_level():
    """The page-walk kernel and the gather reference agree to roundoff for a
    partial-page length, and GQA head grouping (q head h reads kv head
    h // group) matches the zoo convention."""
    rng = np.random.default_rng(0)
    P, ps, kv, d, nh = 6, 8, 2, 32, 4
    pool_k = jnp.asarray(rng.normal(size=(P, ps, kv, d)).astype(np.float32))
    pool_v = jnp.asarray(rng.normal(size=(P, ps, kv, d)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(1, 1, nh, d)).astype(np.float32))
    kn = jnp.asarray(rng.normal(size=(1, 1, kv, d)).astype(np.float32))
    vn = jnp.asarray(rng.normal(size=(1, 1, kv, d)).astype(np.float32))
    table = jnp.asarray([3, 1, 4, 0], jnp.int32)
    length = jnp.int32(19)  # 2 full pages + 3 positions of page index 4
    got = paged_decode_attention(q, kn, vn, pool_k, pool_v, table, length)
    want = _reference(q, kn, vn, pool_k, pool_v, table, length, scale=1.0 / d**0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


def test_kernel_never_reads_unwalked_pages_and_masks_stale_tails():
    """Two tiers of the paged safety invariant, kernel edition: pages the
    length bound never reaches are NOT read at all (NaN there is invisible
    — the page loop stops, no DMA happens), and the masked tail of the
    partial last page contributes exactly-zero softmax weight, so stale
    FINITE values there cannot move the output (the pool-stays-finite
    contract, identical to the gather reference's 0 x value semantics)."""
    rng = np.random.default_rng(1)
    P, ps, kv, d, nh = 6, 8, 2, 32, 2
    pool_k = rng.normal(size=(P, ps, kv, d)).astype(np.float32)
    pool_v = rng.normal(size=(P, ps, kv, d)).astype(np.float32)
    q = jnp.asarray(rng.normal(size=(1, 1, nh, d)).astype(np.float32))
    kn = jnp.asarray(rng.normal(size=(1, 1, kv, d)).astype(np.float32))
    vn = jnp.asarray(rng.normal(size=(1, 1, kv, d)).astype(np.float32))
    table = jnp.asarray([2, 4, 3], jnp.int32)
    length = jnp.int32(11)  # page 2 full, page 4 holds 3 valid positions
    clean = paged_decode_attention(
        q, kn, vn, jnp.asarray(pool_k), jnp.asarray(pool_v), table, length
    )
    pool_k[3] = np.nan  # in the table row, but past the length bound
    pool_v[3] = np.nan
    pool_k[1] = np.nan  # not referenced by this slot at all
    pool_v[5] = np.nan
    pool_k[4, 3:] = 1e6  # stale-but-finite tail of the partial page
    pool_v[4, 3:] = -1e6
    poisoned = paged_decode_attention(
        q, kn, vn, jnp.asarray(pool_k), jnp.asarray(pool_v), table, length
    )
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(poisoned))


def test_zero_length_attends_only_new_token():
    """length=0 (a fresh or inactive lane) walks no pages: the output is
    attention over the single new token — exactly v_new — so idle lanes can
    never touch the pool (not even the null page)."""
    rng = np.random.default_rng(2)
    kv, d = 2, 32
    pool = jnp.full((3, 8, kv, d), jnp.nan, jnp.float32)  # nothing readable
    q = jnp.asarray(rng.normal(size=(1, 1, kv, d)).astype(np.float32))
    kn = jnp.asarray(rng.normal(size=(1, 1, kv, d)).astype(np.float32))
    vn = jnp.asarray(rng.normal(size=(1, 1, kv, d)).astype(np.float32))
    out = paged_decode_attention(
        q, kn, vn, pool, pool, jnp.zeros((2,), jnp.int32), jnp.int32(0)
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(vn), rtol=1e-6)


def test_fallback_reason_interpret_accepts_mosaic_rejects(monkeypatch):
    """On the CPU test mesh (interpret) any geometry runs; forcing
    assert-compiled mode via ACCELERATE_PALLAS_INTERPRET=0 makes the
    lane-unaligned tiny head dim report a fallback reason — the env
    override's two debugging directions."""
    shape = (8, 16, 2, 32)  # [P, ps, KV, D], D=32 unaligned for Mosaic
    assert paged_kernel_fallback_reason(shape, 4, 2) is None
    monkeypatch.setenv("ACCELERATE_PALLAS_INTERPRET", "0")
    reason = paged_kernel_fallback_reason(shape, 4, 2)
    assert reason is not None and "128" in reason
    monkeypatch.setenv("ACCELERATE_PALLAS_INTERPRET", "1")
    assert paged_kernel_fallback_reason(shape, 4, 2) is None


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------


def _rows(model, params, prompts, use_kernels, **kwargs):
    engine = ServingEngine(
        model, params, num_slots=4, max_len=96, page_size=16,
        use_kernels=use_kernels, **kwargs,
    )
    if use_kernels:
        assert engine._use_decode_kernel, engine._kernel_fallback_reason
    return engine.generate_many(prompts, max_new_tokens=6)


def test_kernel_decode_bit_equal_llama_mixed_lengths(llama):
    """The acceptance bar: kernel-enabled paged decode emits the SAME tokens
    as the gather-reference decode at temperature 0, mixed prompt lengths
    (sub-page, page-straddling, multi-page), llama protocol (GQA: 4 q heads
    on 2 kv heads)."""
    model, params = llama
    prompts = _mixed_prompts(model.config.vocab_size, (3, 17, 33, 1))
    ref = _rows(model, params, prompts, use_kernels=False)
    got = _rows(model, params, prompts, use_kernels=True)
    assert all(np.array_equal(a, b) for a, b in zip(ref, got))


def test_kernel_decode_bit_equal_gpt2_chunked_prefill(gpt2):
    """Same gate on the gpt2 protocol (MHA, learned positions), with
    chunked prefill in the mix — the kernel only changes decode, so chunk
    scheduling must compose unchanged."""
    model, params = gpt2
    prompts = _mixed_prompts(model.config.vocab_size, (40, 9, 24), seed=3)
    ref = _rows(model, params, prompts, use_kernels=False, prefill_chunk=16)
    got = _rows(model, params, prompts, use_kernels=True, prefill_chunk=16)
    assert all(np.array_equal(a, b) for a, b in zip(ref, got))


def test_kernel_decode_zero_steady_state_recompiles(llama):
    """Page tables stay fixed-shape jitted ARGUMENTS in the kernel program,
    so after warmup steady state compiles nothing — the serving engine's
    core invariant survives the kernel layer by construction."""
    model, params = llama
    engine = ServingEngine(
        model, params, num_slots=2, max_len=96, page_size=16, use_kernels=True
    )
    engine.warmup()
    mark = engine.compiles.compile_count
    prompts = _mixed_prompts(model.config.vocab_size, (5, 21, 2, 30, 12), seed=7)
    for p in prompts:
        engine.submit(p, max_new_tokens=5)
    engine.run()
    assert engine.compiles.compile_count == mark


def test_unpaged_engine_reports_kernel_fallback(llama):
    """use_kernels on a dense-slab engine cannot engage (the kernel reads
    page tables); the engine must say so — summary names the reason and the
    decode path stays the reference."""
    model, params = llama
    engine = ServingEngine(
        model, params, num_slots=2, max_len=64, paged=False, use_kernels=True
    )
    summary = engine.kernel_summary()
    assert summary["decode_attention"] == "gather_reference"
    assert "paged" in summary["decode_fallback_reason"]


def test_kernels_telemetry_record(llama, tmp_path):
    """One {"kind": "kernels"} record lands in telemetry.jsonl at the first
    step, naming which kernels engaged — kernel coverage is a fleet query,
    not a code read."""
    import json

    from accelerate_tpu.telemetry import Telemetry, TelemetryConfig

    model, params = llama
    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    engine = ServingEngine(
        model, params, num_slots=2, max_len=64, page_size=16,
        telemetry=hub, use_kernels=True,
    )
    engine.submit(np.asarray([5, 6, 7], np.int32), max_new_tokens=2)
    engine.run()
    records = [
        json.loads(line)
        for line in (tmp_path / "telemetry.jsonl").read_text().splitlines()
    ]
    kernels = [r for r in records if r["kind"] == "kernels"]
    assert len(kernels) == 1
    assert kernels[0]["decode_attention"] == "pallas"
    assert kernels[0]["decode_fallback_reason"] is None
