"""Flash attention kernel vs the einsum reference: forward values and all
three gradients, MHA and GQA (runs interpreted on CPU, compiled on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models.attention import dot_product_attention
from accelerate_tpu.ops.flash_attention import flash_attention


def _qkv(b=2, s=256, n=4, kv=4, d=64, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, s, n, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, s, kv, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, s, kv, d)).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("kv", [4, 2])
def test_forward_matches_reference(kv):
    q, k, v = _qkv(kv=kv)
    # explicit 128 blocks: cover the smallest kernel tiling directly
    got = flash_attention(q, k, v, block_q=128, block_k=128)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("kv", [4, 2])
def test_gradients_match_reference(kv):
    q, k, v = _qkv(b=1, s=256, n=4, kv=kv, d=64, seed=1)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=128, block_k=128) ** 2).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-4, err_msg=f"d{name} mismatch"
        )


def test_mask_falls_back_to_reference():
    q, k, v = _qkv(b=1, s=128, n=2, kv=2, d=64)
    mask = jnp.asarray([[1] * 100 + [0] * 28], jnp.int32)
    got = flash_attention(q, k, v, kv_mask=mask)
    want = dot_product_attention(q, k, v, mask=mask[:, None, None, :].astype(bool), causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_odd_seq_falls_back():
    q, k, v = _qkv(b=1, s=96, n=2, kv=2, d=64)
    got = flash_attention(q, k, v)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_auto_attention_dispatch():
    """Long sequences route through the kernel; short ones through einsum —
    both must agree with the reference."""
    from accelerate_tpu.ops.flash_attention import make_auto_attention

    attention = make_auto_attention(min_seq=256)
    q, k, v = _qkv(b=1, s=256, n=2, kv=2, d=64, seed=2)
    np.testing.assert_allclose(
        np.asarray(attention(q, k, v)),
        np.asarray(dot_product_attention(q, k, v, causal=True)),
        rtol=2e-5, atol=2e-5,
    )
    q2, k2, v2 = _qkv(b=1, s=128, n=2, kv=2, d=64, seed=3)
    np.testing.assert_allclose(  # below min_seq: bitwise the einsum path
        np.asarray(attention(q2, k2, v2)),
        np.asarray(dot_product_attention(q2, k2, v2, causal=True)),
        rtol=1e-6,
    )


def test_default_blocks_kernel_matches_reference():
    """The 512-block production default, on a sequence long enough to tile."""
    q, k, v = _qkv(b=1, s=1024, n=2, kv=2, d=64, seed=3)
    got = flash_attention(q, k, v)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_default_blocks_gradients_match_reference():
    """Backward kernels at the production default (unequal 256/512 blocks)."""
    q, k, v = _qkv(b=1, s=1024, n=2, kv=2, d=64, seed=4)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v) ** 2).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-4, err_msg=f"d{name} mismatch"
        )


def test_block_adaptation_keeps_kernel_for_128_multiples():
    """A 128-multiple that neither default block divides (640: 512->256->128
    and 256->128 both halve to the floor) must still match the reference —
    blocks adapt down instead of falling back to the einsum path."""
    q, k, v = _qkv(b=1, s=640, n=2, kv=2, d=64, seed=5)
    got = flash_attention(q, k, v)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
