"""Flash attention kernel vs the einsum reference: forward values and all
three gradients, MHA and GQA (runs interpreted on CPU, compiled on TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models.attention import dot_product_attention
from accelerate_tpu.ops.flash_attention import flash_attention


def _qkv(b=2, s=256, n=4, kv=4, d=64, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, s, n, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, s, kv, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, s, kv, d)).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("kv", [4, 2])
def test_forward_matches_reference(kv):
    q, k, v = _qkv(kv=kv)
    # explicit 128 blocks: cover the smallest kernel tiling directly
    got = flash_attention(q, k, v, block_q=128, block_k=128)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("kv", [4, 2])
def test_gradients_match_reference(kv):
    q, k, v = _qkv(b=1, s=256, n=4, kv=kv, d=64, seed=1)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=128, block_k=128) ** 2).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-4, err_msg=f"d{name} mismatch"
        )


def test_masked_runs_in_kernel():
    """v2: a [B, S] padding mask runs IN the kernel (no einsum fallback)."""
    q, k, v = _qkv(b=2, s=256, n=2, kv=2, d=64)
    mask = jnp.asarray([[1] * 200 + [0] * 56, [1] * 256], jnp.int32)
    got = flash_attention(q, k, v, kv_mask=mask, block_q=128, block_k=128)
    want = dot_product_attention(q, k, v, mask=mask[:, None, None, :].astype(bool), causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_masked_gradients_match_reference():
    q, k, v = _qkv(b=2, s=256, n=2, kv=2, d=64, seed=6)
    mask = jnp.asarray([[1] * 130 + [0] * 126, [1] * 256], jnp.int32)
    mask4 = mask[:, None, None, :].astype(bool)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, kv_mask=mask, block_q=128, block_k=128)
        return ((out * mask[..., None, None]) ** 2).sum()  # loss ignores padding

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask4, causal=True)
        return ((out * mask[..., None, None]) ** 2).sum()

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-4, err_msg=f"d{name} mismatch"
        )


@pytest.mark.parametrize("masked", [False, True])
def test_noncausal_matches_reference(masked):
    """v2 non-causal mode (Bert/T5-encoder): values and gradients."""
    q, k, v = _qkv(b=2, s=256, n=2, kv=2, d=64, seed=7)
    mask = jnp.asarray([[1] * 180 + [0] * 76, [1] * 256], jnp.int32) if masked else None
    mask4 = None if mask is None else mask[:, None, None, :].astype(bool)

    got = flash_attention(q, k, v, kv_mask=mask, causal=False, block_q=128, block_k=128)
    want = dot_product_attention(q, k, v, mask=mask4, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

    w_ = None if mask is None else mask[..., None, None]

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, kv_mask=mask, causal=False, block_q=128, block_k=128)
        return ((out if w_ is None else out * w_) ** 2).sum()

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, mask=mask4, causal=False)
        return ((out if w_ is None else out * w_) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-4, err_msg=f"d{name} mismatch"
        )


@pytest.mark.parametrize("batched_bias", [False, True])
def test_bias_matches_reference(batched_bias):
    """v2 additive bias (T5 relative positions): values and ALL gradients,
    including the bias gradient (batch-reduced for broadcast [1, ...] bias)."""
    b = 2
    q, k, v = _qkv(b=b, s=256, n=2, kv=2, d=64, seed=8)
    rng = np.random.default_rng(8)
    bias = jnp.asarray(rng.normal(size=(b if batched_bias else 1, 2, 256, 256)).astype(np.float32))
    mask = jnp.asarray([[1] * 140 + [0] * 116, [1] * 256], jnp.int32)
    mask4 = mask[:, None, None, :].astype(bool)

    got = flash_attention(
        q, k, v, kv_mask=mask, causal=False, bias=bias, scale=1.0, block_q=128, block_k=128
    )
    want = dot_product_attention(q, k, v, mask=mask4, causal=False, bias=bias, scale=1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

    def loss_flash(q, k, v, bias):
        out = flash_attention(
            q, k, v, kv_mask=mask, causal=False, bias=bias, scale=1.0, block_q=128, block_k=128
        )
        return ((out * mask[..., None, None]) ** 2).sum()

    def loss_ref(q, k, v, bias):
        out = dot_product_attention(q, k, v, mask=mask4, causal=False, bias=bias, scale=1.0)
        return ((out * mask[..., None, None]) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for g, w, name in zip(g1, g2, ["q", "k", "v", "bias"]):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-4, err_msg=f"d{name} mismatch"
        )


def test_causal_bias_matches_reference():
    """Causal + bias (T5 decoder self-attention)."""
    q, k, v = _qkv(b=1, s=256, n=2, kv=2, d=64, seed=9)
    rng = np.random.default_rng(9)
    bias = jnp.asarray(rng.normal(size=(1, 2, 256, 256)).astype(np.float32))
    got = flash_attention(q, k, v, causal=True, bias=bias, scale=1.0, block_q=128, block_k=128)
    want = dot_product_attention(q, k, v, causal=True, bias=bias, scale=1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

    def lf(bias):
        return (flash_attention(q, k, v, causal=True, bias=bias, scale=1.0, block_q=128, block_k=128) ** 2).sum()

    def lr(bias):
        return (dot_product_attention(q, k, v, causal=True, bias=bias, scale=1.0) ** 2).sum()

    np.testing.assert_allclose(
        np.asarray(jax.grad(lf)(bias)), np.asarray(jax.grad(lr)(bias)), rtol=5e-4, atol=5e-4
    )


def test_cross_attention_distinct_lengths():
    """Non-causal q-len != kv-len (T5 cross-attention) runs the kernel."""
    rng = np.random.default_rng(10)
    q = jnp.asarray(rng.normal(size=(2, 128, 2, 64)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 256, 2, 64)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 256, 2, 64)).astype(np.float32))
    mask = jnp.asarray([[1] * 256, [1] * 150 + [0] * 106], jnp.int32)
    got = flash_attention(q, k, v, kv_mask=mask, causal=False, block_q=128, block_k=128)
    want = dot_product_attention(q, k, v, mask=mask[:, None, None, :].astype(bool), causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_fully_masked_rows_are_finite():
    """A fully-padded batch row must give 0 output and 0 gradients, not NaN
    (the einsum path gives a uniform softmax there; either is fine — the
    rows are padding — but NaN would poison the whole residual stream)."""
    q, k, v = _qkv(b=2, s=256, n=2, kv=2, d=64, seed=11)
    mask = jnp.asarray([[0] * 256, [1] * 256], jnp.int32)
    out = flash_attention(q, k, v, kv_mask=mask, causal=False, block_q=128, block_k=128)
    out = np.asarray(out)
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out[0], 0.0)

    def loss(q, k, v):
        out = flash_attention(q, k, v, kv_mask=mask, causal=False, block_q=128, block_k=128)
        return ((out * mask[..., None, None]) ** 2).sum()

    for g in jax.grad(loss, argnums=(0, 1, 2))(q, k, v):
        assert np.isfinite(np.asarray(g)).all()


def test_odd_seq_falls_back():
    q, k, v = _qkv(b=1, s=96, n=2, kv=2, d=64)
    got = flash_attention(q, k, v)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_auto_attention_dispatch():
    """Long sequences route through the kernel; short ones through einsum —
    both must agree with the reference."""
    from accelerate_tpu.ops.flash_attention import make_auto_attention

    attention = make_auto_attention(min_seq=256)
    q, k, v = _qkv(b=1, s=256, n=2, kv=2, d=64, seed=2)
    np.testing.assert_allclose(
        np.asarray(attention(q, k, v)),
        np.asarray(dot_product_attention(q, k, v, causal=True)),
        rtol=2e-5, atol=2e-5,
    )
    q2, k2, v2 = _qkv(b=1, s=128, n=2, kv=2, d=64, seed=3)
    np.testing.assert_allclose(  # below min_seq: bitwise the einsum path
        np.asarray(attention(q2, k2, v2)),
        np.asarray(dot_product_attention(q2, k2, v2, causal=True)),
        rtol=1e-6,
    )


def test_default_blocks_kernel_matches_reference():
    """The 512-block production default, on a sequence long enough to tile."""
    q, k, v = _qkv(b=1, s=1024, n=2, kv=2, d=64, seed=3)
    got = flash_attention(q, k, v)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_default_blocks_gradients_match_reference():
    """Backward kernels at the production default (unequal 256/512 blocks)."""
    q, k, v = _qkv(b=1, s=1024, n=2, kv=2, d=64, seed=4)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v) ** 2).sum()

    def loss_ref(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=5e-4, atol=5e-4, err_msg=f"d{name} mismatch"
        )


def test_block_adaptation_keeps_kernel_for_128_multiples():
    """A 128-multiple that neither default block divides (640: 512->256->128
    and 256->128 both halve to the floor) must still match the reference —
    blocks adapt down instead of falling back to the einsum path."""
    q, k, v = _qkv(b=1, s=640, n=2, kv=2, d=64, seed=5)
    got = flash_attention(q, k, v)
    want = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# zoo wiring: bert / t5 route through the kernel (VERDICT r4 #4 dispatch
# counter — monkeypatching the custom_vjp primal proves the KERNEL ran, not
# the einsum fallback inside flash_attention)
# ---------------------------------------------------------------------------


def _count_kernel_calls(monkeypatch):
    import accelerate_tpu.ops.flash_attention as fa

    calls = {"n": 0}
    orig = fa._flash_attention_bnsd

    def counted(*args, **kwargs):
        calls["n"] += 1
        return orig(*args, **kwargs)

    monkeypatch.setattr(fa, "_flash_attention_bnsd", counted)
    return calls


def test_bert_masked_batch_hits_kernel(monkeypatch):
    """Non-causal + padding mask: bert's attention_fn engages the kernel and
    matches the hook-less model."""
    from accelerate_tpu.models import Bert
    from accelerate_tpu.ops.flash_attention import make_auto_attention

    calls = _count_kernel_calls(monkeypatch)
    model = Bert("bert-tiny")
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 1024, (2, 128)), jnp.int32)
    am = jnp.asarray([[1] * 128, [1] * 70 + [0] * 58], jnp.int32)

    want = np.asarray(model.apply(params, ids, attention_mask=am))
    model.attention_fn = make_auto_attention(min_seq=128, causal=False)
    got = np.asarray(model.apply(params, ids, attention_mask=am))
    assert calls["n"] > 0, "bert attention never reached the flash kernel"
    np.testing.assert_allclose(want, got, rtol=2e-4, atol=2e-4)


def test_t5_hits_kernel_with_bias(monkeypatch):
    """T5 encoder (bias, non-causal, mask) + decoder self-attn (bias, causal)
    + cross-attn (distinct lengths) all route through the kernel and match
    the einsum model; gradients stay finite and close."""
    from accelerate_tpu.models import T5
    from accelerate_tpu.ops.flash_attention import make_auto_attention

    calls = _count_kernel_calls(monkeypatch)
    model = T5("t5-tiny")
    params = model.init(jax.random.key(1))
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, 1024, (2, 256)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 1024, (2, 128)), jnp.int32)
    am = jnp.asarray([[1] * 256, [1] * 150 + [0] * 106], jnp.int32)
    dm = jnp.asarray([[1] * 128, [1] * 90 + [0] * 38], jnp.int32)
    dec = model.shift_right(labels)

    want = np.asarray(model.apply(params, ids, dec, am, dm))
    model.attention_fn = make_auto_attention(min_seq=128)
    got = np.asarray(model.apply(params, ids, dec, am, dm))
    # one trace per attention SITE (the layer stack is a lax.scan, so the
    # body traces once): encoder self + decoder self + cross = 3
    assert calls["n"] >= 3, f"expected every t5 attention site in the kernel, got {calls['n']}"
    real = np.asarray(dm, bool)
    np.testing.assert_allclose(want[real], got[real], rtol=5e-4, atol=5e-4)

    def loss(params):
        logits = model.apply(params, ids, dec, am, dm).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return (nll * dm).sum() / dm.sum()

    grads = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(grads):
        assert np.isfinite(np.asarray(leaf)).all()


def test_prepare_model_wires_noncausal_hook(monkeypatch):
    """prepare_model installs the flash hook for bidirectional models too —
    only on TPU backends, so assert via the factory call."""
    import accelerate_tpu.accelerator as acc_mod
    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import Bert

    wired = {}
    import accelerate_tpu.ops.flash_attention as fa

    orig_factory = fa.make_auto_attention

    def spy(min_seq, causal=True):
        wired["args"] = (min_seq, causal)
        return orig_factory(min_seq, causal=causal)

    monkeypatch.setattr(fa, "make_auto_attention", spy)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    model = Bert("bert-tiny")
    Accelerator().prepare_model(model)
    assert wired["args"][1] is False  # bert: non-causal kernel
    assert model.attention_fn is not None


# ---------------------------------------------------------------------------
# ring-block entry: offset-causal (out, lse) blocks and their merge
# ---------------------------------------------------------------------------


def _merge_blocks(pieces):
    """Online-softmax merge of normalized (out, lse) blocks (the ring rule)."""
    o = m = l = None
    for out, lse in pieces:
        if o is None:
            o, m, l = out.astype(jnp.float32), lse, jnp.ones_like(lse)
            continue
        m_new = jnp.maximum(m, lse)
        co, cb = jnp.exp(m - m_new), jnp.exp(lse - m_new)
        o = o * co[..., None] + out.astype(jnp.float32) * cb[..., None]
        l = l * co + cb
        m = m_new
    return (o / jnp.maximum(l[..., None], 1e-30)).astype(pieces[0][0].dtype)


@pytest.mark.parametrize("masked", [False, True])
def test_block_merge_reconstructs_causal_attention(masked):
    """Ring simulation: the sequence split into 2 KV halves, each attended
    via flash_attention_block at its global offset; the lse merge must
    reconstruct full causal attention exactly."""
    from accelerate_tpu.ops.flash_attention import flash_attention_block

    s = 256
    q, k, v = _qkv(b=2, s=s, n=2, kv=2, d=64, seed=12)
    mask = jnp.asarray([[1] * s, [1] * 170 + [0] * (s - 170)], jnp.int32) if masked else None
    half = s // 2
    # shard 1's query block (positions half..s-1) sees k-half0 fully (past)
    # and k-half1 causally (diagonal)
    q1 = q[:, half:]
    pieces = [
        flash_attention_block(
            q1, k[:, :half], v[:, :half], None if mask is None else mask[:, :half],
            causal=True, q_offset=half, kv_offset=0, block_q=128, block_k=128,
        ),
        flash_attention_block(
            q1, k[:, half:], v[:, half:], None if mask is None else mask[:, half:],
            causal=True, q_offset=half, kv_offset=half, block_q=128, block_k=128,
        ),
    ]
    got = _merge_blocks(pieces)
    mask4 = None if mask is None else mask[:, None, None, :].astype(bool)
    want = dot_product_attention(q, k, v, mask=mask4, causal=True)[:, half:]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

    # shard 0's query block sees k-half1 NOT at all (future: zero-trip loop)
    q0 = q[:, :half]
    future_out, future_lse = flash_attention_block(
        q0, k[:, half:], v[:, half:], None if mask is None else mask[:, half:],
        causal=True, q_offset=0, kv_offset=half, block_q=128, block_k=128,
    )
    np.testing.assert_array_equal(np.asarray(future_out), 0.0)
    assert (np.asarray(future_lse) < -1e28).all()  # merge weight exp(lse)→0


def test_block_merge_gradients_flow_through_lse():
    """The merge weights blocks by lse — its cotangent must reach q/k/v
    (delta' = delta - dlse in the backward kernels)."""
    from accelerate_tpu.ops.flash_attention import flash_attention_block

    s = 256
    q, k, v = _qkv(b=1, s=s, n=2, kv=2, d=64, seed=13)
    half = s // 2

    def loss_blocks(q, k, v):
        q1 = q[:, half:]
        pieces = [
            flash_attention_block(q1, k[:, :half], v[:, :half], causal=True,
                                  q_offset=half, kv_offset=0, block_q=128, block_k=128),
            flash_attention_block(q1, k[:, half:], v[:, half:], causal=True,
                                  q_offset=half, kv_offset=half, block_q=128, block_k=128),
        ]
        return (_merge_blocks(pieces).astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        out = dot_product_attention(q, k, v, causal=True)[:, half:]
        return (out.astype(jnp.float32) ** 2).sum()

    g1 = jax.grad(loss_blocks, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4, err_msg=f"d{name} mismatch"
        )


def test_block_noncausal_matches_plain():
    from accelerate_tpu.ops.flash_attention import flash_attention_block

    q, k, v = _qkv(b=2, s=128, n=4, kv=2, d=64, seed=14)  # GQA too
    out, lse = flash_attention_block(q, k, v, causal=False, block_q=128, block_k=128)
    want = dot_product_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5)
    assert lse.shape == (2, 128, 4) and np.isfinite(np.asarray(lse)).all()
