"""Partition-rule engine unit tests (parallel/sharding.py)."""

import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from accelerate_tpu.parallel.sharding import (
    PartitionRules,
    infer_shardings,
    shard_tree,
    shardings_like,
)


@pytest.fixture
def mesh():
    devices = jax.devices()
    import numpy as np

    return Mesh(np.array(devices[:2]), ("fsdp",))


def test_shardings_like_matches_by_path_not_shape(mesh):
    """Two same-shaped params with different shardings must each get their own
    sharding for the Adam moments (VERDICT r2 weak #5: shape-only matching is
    first-match-wins and silently wrong)."""
    params = {
        "a": jnp.zeros((4, 8)),
        "b": jnp.zeros((4, 8)),
    }
    shard_a = NamedSharding(mesh, PartitionSpec("fsdp", None))
    shard_b = NamedSharding(mesh, PartitionSpec(None, "fsdp"))
    params_shardings = {"a": shard_a, "b": shard_b}

    tx = optax.adam(1e-3)
    state_shapes = jax.eval_shape(tx.init, params)
    out = shardings_like(state_shapes, params, params_shardings, mesh)

    adam_state = out[0]  # ScaleByAdamState(count, mu, nu)
    assert adam_state.mu["a"].spec == shard_a.spec
    assert adam_state.mu["b"].spec == shard_b.spec
    assert adam_state.nu["a"].spec == shard_a.spec
    assert adam_state.nu["b"].spec == shard_b.spec
    # scalar count replicated
    assert adam_state.count.spec == PartitionSpec()


def test_shardings_like_prefers_longest_suffix(mesh):
    """A top-level param whose path is a suffix of a nested one must not
    capture the nested param's moments."""
    params = {
        "w": jnp.zeros((4, 8)),
        "layers": {"w": jnp.zeros((4, 8))},
    }
    shard_top = NamedSharding(mesh, PartitionSpec("fsdp", None))
    shard_nested = NamedSharding(mesh, PartitionSpec(None, "fsdp"))
    params_shardings = {"w": shard_top, "layers": {"w": shard_nested}}

    tx = optax.adam(1e-3)
    state_shapes = jax.eval_shape(tx.init, params)
    out = shardings_like(state_shapes, params, params_shardings, mesh)
    assert out[0].mu["w"].spec == shard_top.spec
    assert out[0].mu["layers"]["w"].spec == shard_nested.spec


def test_shardings_like_unmatched_replicated(mesh):
    """State leaves that are not param-tree copies fall back to replication."""
    params = {"a": jnp.zeros((4, 8))}
    shardings = {"a": NamedSharding(mesh, PartitionSpec("fsdp", None))}
    # sgd with momentum keeps a param copy; adamw scale keeps count scalars
    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.sgd(1e-2, momentum=0.9))
    state_shapes = jax.eval_shape(tx.init, params)
    out = shardings_like(state_shapes, params, shardings, mesh)
    flat = jax.tree.leaves(out)
    assert all(isinstance(s, NamedSharding) for s in flat)
    # the momentum buffer (trace) must pick up the param sharding
    trace_shardings = [s for s, l in zip(jax.tree.leaves(out), jax.tree.leaves(state_shapes)) if l.shape == (4, 8)]
    assert all(s.spec == PartitionSpec("fsdp", None) for s in trace_shardings)


def test_infer_shardings_rules(mesh):
    rules = PartitionRules([("wq", (None, "fsdp"))])
    tree = {"layers": {"wq": jnp.zeros((8, 8)), "tiny": jnp.zeros((2,))}}
    out = infer_shardings(tree, mesh, rules)
    assert out["layers"]["wq"].spec == PartitionSpec(None, "fsdp")
    assert out["layers"]["tiny"].spec == PartitionSpec()  # too small for auto-fsdp


def test_shard_tree_places(mesh):
    rules = PartitionRules([("wq", (None, "fsdp"))])
    tree = {"wq": jnp.ones((8, 8))}
    shardings = infer_shardings(tree, mesh, rules)
    placed = shard_tree(tree, shardings)
    assert placed["wq"].sharding.spec == PartitionSpec(None, "fsdp")
