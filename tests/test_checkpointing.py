"""Sharded checkpoint format: per-process chunk writing wired into
save_state/load_state (reference FSDP SHARDED_STATE_DICT, utils/fsdp_utils.py:85-96),
including cross-mesh resume."""

import glob
import os

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, FullyShardedDataParallelPlugin, ParallelismConfig
from accelerate_tpu.checkpointing import (
    is_sharded_checkpoint,
    load_model_weights_sharded,
    save_model_weights_sharded,
)
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState


class BigLinear:
    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {
            "w": jax.random.normal(k1, (256, 64), jnp.float32),
            "b": jax.random.normal(k2, (64,), jnp.float32),
        }

    @staticmethod
    def apply(params, x):
        return x @ params["w"] + params["b"]


def _loss(params, batch):
    out = BigLinear.apply(params, batch["x"])
    return jnp.mean((out - batch["y"]) ** 2)


def _batch(n=16):
    rng = np.random.default_rng(0)
    return {
        "x": jnp.asarray(rng.normal(size=(n, 256)).astype(np.float32)),
        "y": jnp.asarray(rng.normal(size=(n, 64)).astype(np.float32)),
    }


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _make(fsdp):
    plugin = FullyShardedDataParallelPlugin(stage=3, min_weight_size=1024)
    acc = Accelerator(parallelism=ParallelismConfig(fsdp=fsdp), fsdp_plugin=plugin)
    model = acc.prepare(BigLinear())
    opt = acc.prepare_optimizer(optax.adam(1e-2))
    return acc, model, opt


def test_sharded_writer_roundtrip_cross_mesh(tmp_path):
    """save_model_weights_sharded on fsdp=4 reassembles bitwise on fsdp=2."""
    acc, model, _ = _make(4)
    save_model_weights_sharded(model.params, str(tmp_path))
    reference = jax.device_get(model.params)
    assert is_sharded_checkpoint(str(tmp_path))

    _reset()
    acc2, model2, _ = _make(2)
    flat = load_model_weights_sharded(str(tmp_path))
    np.testing.assert_array_equal(flat["w"], np.asarray(reference["w"]))
    np.testing.assert_array_equal(flat["b"], np.asarray(reference["b"]))


def test_save_state_sharded_load_state_cross_mesh(tmp_path):
    """Full save_state(sharded=True) on fsdp=4 → load_state on fsdp=2:
    params bitwise equal, training continues (VERDICT r2 item 1a)."""
    acc, model, opt = _make(4)
    batch = _batch()
    for _ in range(3):
        acc.backward(_loss, batch)
        opt.step()
        opt.zero_grad()
    reference = jax.device_get(model.params)
    reference_opt = jax.device_get(opt.opt_state)
    acc.save_state(str(tmp_path / "ckpt"), sharded=True)
    # sharded format on disk: per-process chunk files, no monolithic file —
    # for the optimizer moments (the largest ZeRO component) too
    assert glob.glob(str(tmp_path / "ckpt" / "model_0.shard*.index.json"))
    assert glob.glob(str(tmp_path / "ckpt" / "optimizer_0.shard*.index.json"))
    assert not os.path.exists(tmp_path / "ckpt" / "model_0.safetensors")
    assert not os.path.exists(tmp_path / "ckpt" / "optimizer_0.npz")

    _reset()
    acc2, model2, opt2 = _make(2)
    acc2.load_state(str(tmp_path / "ckpt"))
    restored = jax.device_get(model2.params)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(reference["w"]))
    np.testing.assert_array_equal(np.asarray(restored["b"]), np.asarray(reference["b"]))
    for got, want in zip(jax.tree.leaves(jax.device_get(opt2.opt_state)), jax.tree.leaves(reference_opt)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert opt2.step_count == 3
    # params landed back on the new mesh's shardings and training continues
    assert model2.params["w"].sharding.spec == model2.params_shardings["w"].spec
    loss = acc2.backward(_loss, batch)
    opt2.step()
    assert np.isfinite(float(loss))


def test_lost_shard_file_fails_loudly(tmp_path):
    """A tensor partially covered by surviving shards must not load as
    uninitialized memory (operator lost one shard file)."""
    acc, model, _ = _make(4)
    save_model_weights_sharded(model.params, str(tmp_path))
    shards = sorted(glob.glob(str(tmp_path / "model.shard*.index.json")))
    # fake a lost process-shard: strip one process's chunks from its index so
    # the union no longer tiles the tensors (single-host CI writes one file)
    import json

    with open(shards[0]) as f:
        index = json.load(f)
    dropped = {k: v for j, (k, v) in enumerate(sorted(index["chunks"].items())) if j > 0}
    assert len(dropped) < len(index["chunks"])
    index["chunks"] = dropped
    with open(shards[0], "w") as f:
        json.dump(index, f)
    with pytest.raises(FileNotFoundError, match="incomplete"):
        load_model_weights_sharded(str(tmp_path))


def test_resave_other_format_does_not_shadow(tmp_path):
    """sharded save then non-sharded save into the same dir: the loader must
    restore the NEWER state, not the stale sharded files."""
    acc, model, opt = _make(4)
    batch = _batch()
    acc.save_state(str(tmp_path / "ckpt"), sharded=True)
    acc.backward(_loss, batch)
    opt.step()
    opt.zero_grad()
    newer = jax.device_get(model.params)
    acc.save_state(str(tmp_path / "ckpt"))  # default format, same dir
    assert not glob.glob(str(tmp_path / "ckpt" / "model_0.shard*"))

    _reset()
    acc2, model2, opt2 = _make(4)
    acc2.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(model2.params)["w"]), np.asarray(newer["w"])
    )


def test_unsharded_save_still_loads(tmp_path):
    """Default (gathered) path unchanged and auto-detected on load."""
    acc, model, opt = _make(4)
    reference = jax.device_get(model.params)
    acc.save_state(str(tmp_path / "ckpt"))
    assert not is_sharded_checkpoint(str(tmp_path / "ckpt"), "model_0.safetensors")

    _reset()
    acc2, model2, opt2 = _make(2)
    acc2.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(model2.params)["w"]), np.asarray(reference["w"])
    )
