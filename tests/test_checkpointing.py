"""Sharded checkpoint format: per-process chunk writing wired into
save_state/load_state (reference FSDP SHARDED_STATE_DICT, utils/fsdp_utils.py:85-96),
including cross-mesh resume."""

import glob
import os

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, FullyShardedDataParallelPlugin, ParallelismConfig
from accelerate_tpu.checkpointing import (
    is_sharded_checkpoint,
    load_model_weights_sharded,
    save_model_weights_sharded,
)
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState


class BigLinear:
    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {
            "w": jax.random.normal(k1, (256, 64), jnp.float32),
            "b": jax.random.normal(k2, (64,), jnp.float32),
        }

    @staticmethod
    def apply(params, x):
        return x @ params["w"] + params["b"]


def _loss(params, batch):
    out = BigLinear.apply(params, batch["x"])
    return jnp.mean((out - batch["y"]) ** 2)


def _batch(n=16):
    rng = np.random.default_rng(0)
    return {
        "x": jnp.asarray(rng.normal(size=(n, 256)).astype(np.float32)),
        "y": jnp.asarray(rng.normal(size=(n, 64)).astype(np.float32)),
    }


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _make(fsdp):
    plugin = FullyShardedDataParallelPlugin(stage=3, min_weight_size=1024)
    acc = Accelerator(parallelism=ParallelismConfig(fsdp=fsdp), fsdp_plugin=plugin)
    model = acc.prepare(BigLinear())
    opt = acc.prepare_optimizer(optax.adam(1e-2))
    return acc, model, opt


def test_sharded_writer_roundtrip_cross_mesh(tmp_path):
    """save_model_weights_sharded on fsdp=4 reassembles bitwise on fsdp=2."""
    acc, model, _ = _make(4)
    save_model_weights_sharded(model.params, str(tmp_path))
    reference = jax.device_get(model.params)
    assert is_sharded_checkpoint(str(tmp_path))

    _reset()
    acc2, model2, _ = _make(2)
    flat = load_model_weights_sharded(str(tmp_path))
    np.testing.assert_array_equal(flat["w"], np.asarray(reference["w"]))
    np.testing.assert_array_equal(flat["b"], np.asarray(reference["b"]))


def test_save_state_sharded_load_state_cross_mesh(tmp_path):
    """Full save_state(sharded=True) on fsdp=4 → load_state on fsdp=2:
    params bitwise equal, training continues (VERDICT r2 item 1a)."""
    acc, model, opt = _make(4)
    batch = _batch()
    for _ in range(3):
        acc.backward(_loss, batch)
        opt.step()
        opt.zero_grad()
    reference = jax.device_get(model.params)
    reference_opt = jax.device_get(opt.opt_state)
    acc.save_state(str(tmp_path / "ckpt"), sharded=True)
    # sharded format on disk: per-process chunk files, no monolithic file —
    # for the optimizer moments (the largest ZeRO component) too
    assert glob.glob(str(tmp_path / "ckpt" / "model_0.shard*.index.json"))
    assert glob.glob(str(tmp_path / "ckpt" / "optimizer_0.shard*.index.json"))
    assert not os.path.exists(tmp_path / "ckpt" / "model_0.safetensors")
    assert not os.path.exists(tmp_path / "ckpt" / "optimizer_0.npz")

    _reset()
    acc2, model2, opt2 = _make(2)
    acc2.load_state(str(tmp_path / "ckpt"))
    restored = jax.device_get(model2.params)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.asarray(reference["w"]))
    np.testing.assert_array_equal(np.asarray(restored["b"]), np.asarray(reference["b"]))
    for got, want in zip(jax.tree.leaves(jax.device_get(opt2.opt_state)), jax.tree.leaves(reference_opt)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert opt2.step_count == 3
    # params landed back on the new mesh's shardings and training continues
    assert model2.params["w"].sharding.spec == model2.params_shardings["w"].spec
    loss = acc2.backward(_loss, batch)
    opt2.step()
    assert np.isfinite(float(loss))


def test_lost_shard_file_fails_loudly(tmp_path):
    """A tensor partially covered by surviving shards must not load as
    uninitialized memory (operator lost one shard file)."""
    acc, model, _ = _make(4)
    save_model_weights_sharded(model.params, str(tmp_path))
    shards = sorted(glob.glob(str(tmp_path / "model.shard*.index.json")))
    # fake a lost process-shard: strip one process's chunks from its index so
    # the union no longer tiles the tensors (single-host CI writes one file)
    import json

    with open(shards[0]) as f:
        index = json.load(f)
    dropped = {k: v for j, (k, v) in enumerate(sorted(index["chunks"].items())) if j > 0}
    assert len(dropped) < len(index["chunks"])
    index["chunks"] = dropped
    with open(shards[0], "w") as f:
        json.dump(index, f)
    with pytest.raises(FileNotFoundError, match="incomplete"):
        load_model_weights_sharded(str(tmp_path))


def test_resave_other_format_does_not_shadow(tmp_path):
    """sharded save then non-sharded save into the same dir: the loader must
    restore the NEWER state, not the stale sharded files."""
    acc, model, opt = _make(4)
    batch = _batch()
    acc.save_state(str(tmp_path / "ckpt"), sharded=True)
    acc.backward(_loss, batch)
    opt.step()
    opt.zero_grad()
    newer = jax.device_get(model.params)
    acc.save_state(str(tmp_path / "ckpt"))  # default format, same dir
    assert not glob.glob(str(tmp_path / "ckpt" / "model_0.shard*"))

    _reset()
    acc2, model2, opt2 = _make(4)
    acc2.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(model2.params)["w"]), np.asarray(newer["w"])
    )


# ---------------------------------------------------------------------------
# crash injection: a kill at any instant of save_state must never lose the run
# ---------------------------------------------------------------------------


def test_kill_mid_save_keeps_previous_checkpoint_resumable(tmp_path):
    """Kill while staging (a truncated file in the .tmp dir): latest_valid()
    skips the torn dir, load_state restores the previous checkpoint, and the
    next save garbage-collects the debris."""
    from accelerate_tpu import CheckpointManager, fault_tolerance

    acc, model, opt = _make(4)
    batch = _batch()
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path), handle_signals=())
    acc.backward(_loss, batch)
    opt.step()
    opt.zero_grad()
    good = jax.device_get(model.params)
    manager.save(step=1)
    assert manager.latest_valid() == str(tmp_path / "checkpoint_1")

    acc.backward(_loss, batch)
    opt.step()
    opt.zero_grad()

    def tear(stage, directory):
        if stage == "staged":
            victim = os.path.join(directory, "model_0.safetensors")
            if not os.path.exists(victim):
                victim = os.path.join(directory, "model_0.npz")
            with open(victim, "r+b") as f:
                f.truncate(8)
            raise RuntimeError("simulated kill mid-save")

    fault_tolerance.fault_injection_hook = tear
    try:
        with pytest.raises(RuntimeError, match="simulated kill"):
            manager.save(step=2)
    finally:
        fault_tolerance.fault_injection_hook = None

    # torn staging dir on disk, but discovery never surfaces it
    assert glob.glob(str(tmp_path / "checkpoint_2.tmp"))
    assert not (tmp_path / "checkpoint_2").exists()
    assert manager.latest_valid() == str(tmp_path / "checkpoint_1")

    _reset()
    acc2, model2, _ = _make(4)
    manager2 = CheckpointManager(acc2, checkpoint_dir=str(tmp_path), handle_signals=())
    resume = manager2.resume("auto")
    assert resume is not None and resume.step == 1
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(model2.params)["w"]), np.asarray(good["w"])
    )
    # the next save garbage-collects the torn dir
    manager2.save(step=3)
    assert not glob.glob(str(tmp_path / "*.tmp"))
    assert manager2.latest_valid() == str(tmp_path / "checkpoint_3")


def test_kill_after_manifest_before_rename_is_skipped(tmp_path):
    """A staging dir that is COMPLETE (manifest written) but never renamed is
    still invisible to auto-resume: commit is the rename, nothing earlier."""
    from accelerate_tpu import CheckpointManager, fault_tolerance, latest_valid_checkpoint

    acc, model, opt = _make(2)
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path), handle_signals=())
    manager.save(step=1)

    def kill_before_rename(stage, directory):  # noqa: ARG001
        if stage == "manifest":
            raise RuntimeError("simulated kill before rename")

    fault_tolerance.fault_injection_hook = kill_before_rename
    try:
        with pytest.raises(RuntimeError, match="before rename"):
            manager.save(step=2)
    finally:
        fault_tolerance.fault_injection_hook = None
    assert (tmp_path / "checkpoint_2.tmp" / "manifest.json").exists()
    assert latest_valid_checkpoint(str(tmp_path)) == str(tmp_path / "checkpoint_1")


def test_externally_damaged_checkpoint_is_skipped(tmp_path):
    """Bit-rot / partial deletion AFTER commit: the manifest checksums catch
    it and latest_valid falls back to the older complete checkpoint."""
    from accelerate_tpu import CheckpointManager

    acc, model, opt = _make(2)
    batch = _batch()
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path), handle_signals=())
    manager.save(step=1)
    acc.backward(_loss, batch)
    opt.step()
    opt.zero_grad()
    manager.save(step=2)
    assert manager.latest_valid() == str(tmp_path / "checkpoint_2")

    # flip bytes in the newest checkpoint's weights file
    victims = glob.glob(str(tmp_path / "checkpoint_2" / "model_0.*"))
    with open(victims[0], "r+b") as f:
        f.seek(0)
        f.write(b"\xff" * 16)
    assert manager.latest_valid() == str(tmp_path / "checkpoint_1")


def test_sigterm_triggers_one_boundary_save_and_resume_is_bit_exact(tmp_path):
    """SIGTERM mid-loop → exactly one save at the next step boundary, loop
    exits; a fresh process resuming with "auto" sees the SAME next batch
    (set_epoch + seedable sampler + skip_first_batches), bit for bit."""
    import signal

    from accelerate_tpu import CheckpointManager

    def make_loader(acc):
        data = np.arange(64 * 8, dtype=np.float32).reshape(64, 8)
        return acc.prepare_data_loader(
            [{"x": row} for row in data], batch_size=8, shuffle=True, seed=123
        )

    # reference: the batch sequence of an uninterrupted epoch
    acc, model, opt = _make(2)
    loader = make_loader(acc)
    reference = [np.asarray(b["x"]) for b in loader]

    _reset()
    acc, model, opt = _make(2)
    loader = make_loader(acc)
    manager = CheckpointManager(acc, checkpoint_dir=str(tmp_path), save_interval=100)
    try:
        saves = 0
        step = 0
        exited = False
        loader.set_epoch(0)
        for batch in loader:
            step += 1
            if step == 3:
                os.kill(os.getpid(), signal.SIGTERM)  # handler flips the flag only
            if manager.should_save(step):
                manager.save(step, epoch=0)
                saves += 1
            if manager.exit_requested:
                exited = True
                break
        assert exited and saves == 1 and step == 3
        assert manager.latest_valid() == str(tmp_path / "checkpoint_3")
    finally:
        manager.restore_signal_handlers()

    _reset()
    acc2, model2, opt2 = _make(2)
    loader2 = make_loader(acc2)
    manager2 = CheckpointManager(acc2, checkpoint_dir=str(tmp_path), handle_signals=())
    resume = manager2.resume("auto")
    assert resume.step == 3 and resume.epoch == 0
    assert resume.dataloaders == [{"epoch": 0, "position": 3}]
    loader2.set_epoch(0)
    resumed = manager2.resumed_loader(loader2, resume, epoch=0)
    nxt = next(iter(resumed))
    np.testing.assert_array_equal(np.asarray(nxt["x"]), reference[3])
    # a save during the resumed epoch records the ABSOLUTE position
    assert resumed.position == 4


_PREEMPTIBLE_TRAIN_SCRIPT = """
import os, signal, sys
import numpy as np
import optax
import jax, jax.numpy as jnp
from accelerate_tpu import Accelerator, CheckpointManager

mode, ckpt_dir = sys.argv[1], sys.argv[2]  # mode: ref | run | resume

class Tiny:
    def init(self, rng): return {"w": jax.random.normal(rng, (8, 4), jnp.float32)}
    @staticmethod
    def apply(params, x): return x @ params["w"]

def loss(params, batch): return jnp.mean(Tiny.apply(params, batch["x"]) ** 2)

acc = Accelerator()
model = acc.prepare(Tiny())
opt = acc.prepare_optimizer(optax.sgd(1e-2))
data = [{"x": np.arange(8, dtype=np.float32) * (i + 1)} for i in range(48)]
loader = acc.prepare_data_loader(data, batch_size=8, shuffle=True, seed=7)
manager = CheckpointManager(acc, checkpoint_dir=ckpt_dir, save_interval=1000)
resume = manager.resume("auto" if mode == "resume" else None)
step = resume.step if resume else 0
loader.set_epoch(0)
epoch_loader = manager.resumed_loader(loader, resume, epoch=0)
for batch in epoch_loader:
    step += 1
    print(f"STEP {step} SUM {float(jnp.sum(batch['x'])):.1f}", flush=True)
    acc.backward(loss, batch)
    opt.step()
    opt.zero_grad()
    if mode == "run" and step == 2:
        os.kill(os.getpid(), signal.SIGTERM)  # fake the spot-VM grace signal
    if manager.should_save(step):
        manager.save(step, epoch=0)
    if manager.exit_requested:
        print("CLEAN_EXIT", flush=True)
        sys.exit(0)
print("DONE", flush=True)
"""


def test_sigterm_process_exits_cleanly_and_autoresumes(tmp_path):
    """Full process-level drill: SIGTERM mid-epoch → exactly one boundary
    save + exit code 0; a NEW process with resume="auto" continues at the
    next step and consumes the same batches as an uninterrupted run."""
    import subprocess
    import sys as _sys

    script = tmp_path / "train.py"
    script.write_text(_PREEMPTIBLE_TRAIN_SCRIPT)

    def launch(mode, ckpt):
        result = subprocess.run(
            [_sys.executable, str(script), mode, str(ckpt)],
            capture_output=True, text=True, timeout=300,
        )
        assert result.returncode == 0, result.stdout + result.stderr
        return result.stdout

    reference = launch("ref", tmp_path / "ref_ckpts")
    ref_steps = [l for l in reference.splitlines() if l.startswith("STEP")]
    assert len(ref_steps) == 6  # 48 samples / batch 8

    run_out = launch("run", tmp_path / "ckpts")
    assert "CLEAN_EXIT" in run_out
    assert [l for l in run_out.splitlines() if l.startswith("STEP")] == ref_steps[:2]
    assert os.listdir(tmp_path / "ckpts") == ["checkpoint_2"]  # exactly one save

    resume_out = launch("resume", tmp_path / "ckpts")
    resumed_steps = [l for l in resume_out.splitlines() if l.startswith("STEP")]
    # picks up at step 3 and the batch stream is bit-exact the reference's
    assert resumed_steps == ref_steps[2:]
    assert "DONE" in resume_out


def test_unsharded_save_still_loads(tmp_path):
    """Default (gathered) path unchanged and auto-detected on load."""
    acc, model, opt = _make(4)
    reference = jax.device_get(model.params)
    acc.save_state(str(tmp_path / "ckpt"))
    assert not is_sharded_checkpoint(str(tmp_path / "ckpt"), "model_0.safetensors")

    _reset()
    acc2, model2, opt2 = _make(2)
    acc2.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(model2.params)["w"]), np.asarray(reference["w"])
    )
