"""Model zoo tests: shapes, param counts, TP sharding, training smoke."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import Bert, Llama, build_model, get_config, param_count
from accelerate_tpu.parallel.sharding import PartitionRules, infer_shardings
from accelerate_tpu.state import PartialState
from accelerate_tpu.utils import next_rng_key, set_seed


def test_llama_forward_shape():
    model = Llama("llama-tiny")
    set_seed(0)
    params = model.init(next_rng_key())
    ids = jnp.arange(32).reshape(2, 16) % 1024
    logits = model.apply(params, ids)
    assert logits.shape == (2, 16, 1024)
    assert np.isfinite(np.asarray(logits)).all()


def test_llama_param_count_matches_config():
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    actual = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert actual == param_count(get_config("llama-tiny"))


def test_bert_param_count_matches_config():
    model = Bert("bert-tiny")
    params = model.init(jax.random.key(0))
    actual = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert actual == param_count(get_config("bert-tiny"))


def test_bert_forward_shape():
    model = Bert("bert-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.arange(32).reshape(2, 16) % 1024
    logits = model.apply(params, ids, attention_mask=jnp.ones_like(ids))
    assert logits.shape == (2, 2)


def test_llama_causality():
    """Changing a future token must not affect earlier logits."""
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.arange(16)[None, :] % 1024
    logits1 = model.apply(params, ids)
    ids2 = ids.at[0, -1].set(7)
    logits2 = model.apply(params, ids2)
    np.testing.assert_allclose(np.asarray(logits1[0, :-1]), np.asarray(logits2[0, :-1]), atol=1e-5)


def test_llama_tp_sharding_applied():
    state = PartialState(parallelism=ParallelismConfig(tensor=4))
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    rules = PartitionRules(model.partition_rules())
    shardings = infer_shardings(params, state.mesh, rules)
    wq_spec = shardings["layers"]["wq"].spec
    # leading dim carries the (size-1 here) pipeline axis; last dim is TP
    assert wq_spec == jax.sharding.PartitionSpec("pipeline", None, "tensor")
    wo_spec = shardings["layers"]["wo"].spec
    assert wo_spec == jax.sharding.PartitionSpec("pipeline", "tensor", None)


def test_llama_tp_forward_matches_single_device():
    """TP=4 sharded forward must equal the unsharded forward numerically."""
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.arange(32).reshape(2, 16) % 1024
    expected = model.apply(params, ids)

    accelerator = Accelerator(parallelism=ParallelismConfig(tensor=4))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_llama_trains():
    accelerator = Accelerator(parallelism=ParallelismConfig(fsdp=2, tensor=2))
    model = Llama("llama-tiny")
    loss_fn = Llama.loss_fn(model)
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(rng.integers(0, 1024, (8, 32)), jnp.int32)}
    losses = []
    for _ in range(10):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # memorizing one batch


def test_build_model_registry():
    assert isinstance(build_model("llama-tiny"), Llama)
    assert isinstance(build_model("bert-base"), Bert)
    with pytest.raises(KeyError):
        build_model("gpt-unknown")


# -- gpt2 family --------------------------------------------------------------


def test_gpt2_forward_shape_and_param_count():
    from accelerate_tpu.models import GPT2
    from accelerate_tpu.models.config import get_config, param_count

    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(0))
    counted = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert counted == param_count(get_config("gpt2-tiny"))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (2, 12)), jnp.int32)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 12, 1024)
    assert logits.dtype == jnp.float32


def test_gpt2_causality():
    """Changing a future token must not change past logits."""
    from accelerate_tpu.models import GPT2

    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(1))
    ids = np.random.default_rng(1).integers(0, 1024, (1, 10)).astype(np.int32)
    base = np.asarray(model.apply(params, jnp.asarray(ids)))
    ids2 = ids.copy()
    ids2[0, -1] = (ids2[0, -1] + 7) % 1024
    changed = np.asarray(model.apply(params, jnp.asarray(ids2)))
    np.testing.assert_allclose(base[0, :-1], changed[0, :-1], atol=1e-5)
    assert not np.allclose(base[0, -1], changed[0, -1])


def test_gpt2_tp_forward_matches_single_device():
    from accelerate_tpu.models import GPT2

    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(2))
    ids = jnp.asarray(np.random.default_rng(2).integers(0, 1024, (4, 16)), jnp.int32)
    expected = model.apply(params, ids)

    accelerator = Accelerator(parallelism=ParallelismConfig(tensor=4))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_gpt2_trains():
    from accelerate_tpu.models import GPT2

    accelerator = Accelerator(parallelism=ParallelismConfig(fsdp=2, tensor=2))
    model = GPT2("gpt2-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    loss_fn = GPT2.loss_fn(model)
    batch = {"input_ids": jnp.asarray(np.random.default_rng(3).integers(0, 1024, (8, 32)), jnp.int32)}
    losses = []
    for _ in range(10):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_gpt2_masked_loss_ignores_padding():
    from accelerate_tpu.models import GPT2

    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(4))
    rng = np.random.default_rng(4)
    ids = rng.integers(0, 1024, (2, 12)).astype(np.int32)
    mask = np.ones((2, 12), np.int32)
    mask[1, 8:] = 0
    loss_fn = GPT2.loss_fn(model)
    base = float(loss_fn(params, {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)}))
    ids2 = ids.copy()
    ids2[1, 9:] = 0  # mutate only padded positions
    got = float(loss_fn(params, {"input_ids": jnp.asarray(ids2), "attention_mask": jnp.asarray(mask)}))
    np.testing.assert_allclose(base, got, rtol=1e-6)


def test_gpt2_streamed_dispatch_matches_full():
    from accelerate_tpu.big_modeling import cpu_offload
    from accelerate_tpu.models import GPT2

    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(5))
    ids = jnp.asarray(np.random.default_rng(5).integers(0, 1024, (2, 10)), jnp.int32)
    full = model.apply(params, ids)
    streamed = cpu_offload(model, params, dtype=jnp.float32)
    got = streamed(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full), atol=1e-4)


def test_gpt2_in_registry():
    from accelerate_tpu.models import GPT2

    assert isinstance(build_model("gpt2-124m"), GPT2)


def test_gpt2_generate_kv_cache_matches_recompute():
    from accelerate_tpu.models import GPT2
    from accelerate_tpu.models.generation import generate as gen

    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(6))
    ids = np.random.default_rng(6).integers(0, 1024, (2, 7)).astype(np.int32)
    out = gen(model, params, jnp.asarray(ids), max_new_tokens=5)
    assert out.shape == (2, 12)

    manual = ids.copy()
    for _ in range(5):
        logits = model.apply(params, jnp.asarray(manual))
        nxt = np.argmax(np.asarray(logits[:, -1], np.float32), axis=-1)
        manual = np.concatenate([manual, nxt[:, None].astype(np.int32)], axis=1)
    np.testing.assert_array_equal(out, manual)


def test_gpt2_streamed_generate_matches_generate():
    """Offloaded gpt2 decode (StreamedModel.generate) == in-memory generate."""
    from accelerate_tpu.big_modeling import cpu_offload, dispatch_model
    from accelerate_tpu.models import GPT2
    from accelerate_tpu.models.generation import generate as gen

    model = GPT2("gpt2-tiny")
    params = model.init(jax.random.key(7))
    ids = jnp.asarray(np.random.default_rng(7).integers(0, 1024, (2, 6)), jnp.int32)
    expected = gen(model, params, ids, max_new_tokens=4)

    streamed = cpu_offload(model, params, dtype=jnp.float32)
    got = streamed.generate(ids, max_new_tokens=4)
    np.testing.assert_array_equal(got, expected)
    # grouping must not change the decode either
    cfg = model.config
    dm = {k: "cpu" if k.startswith("layers.") else "device" for k in streamed.hf_device_map}
    narrow = dispatch_model(model, params, dm, dtype=jnp.float32, stream_window_bytes=1)
    assert narrow.group_size == 1
    np.testing.assert_array_equal(narrow.generate(ids, max_new_tokens=4), expected)


def test_learned_position_overflow_raises():
    """Learned-position models must reject sequences past max_seq_len
    (jnp.take would silently clamp to the last position row)."""
    from accelerate_tpu.models import Bert, GPT2
    from accelerate_tpu.models.generation import generate as gen

    gpt2 = GPT2("gpt2-tiny")  # max_seq_len 256
    params = gpt2.init(jax.random.key(8))
    long_ids = jnp.zeros((1, 257), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        gpt2.apply(params, long_ids)
    with pytest.raises(ValueError, match="max_seq_len"):
        gen(gpt2, params, jnp.zeros((1, 250), jnp.int32), max_new_tokens=10)

    bert = Bert("bert-tiny")  # max_seq_len 128
    bparams = bert.init(jax.random.key(8))
    with pytest.raises(ValueError, match="max_seq_len"):
        bert.apply(bparams, jnp.zeros((1, 129), jnp.int32))


def test_streamed_learned_position_overflow_raises():
    from accelerate_tpu.big_modeling import cpu_offload
    from accelerate_tpu.models import GPT2

    model = GPT2("gpt2-tiny")  # max_seq_len 256
    params = model.init(jax.random.key(9))
    streamed = cpu_offload(model, params, dtype=jnp.float32)
    with pytest.raises(ValueError, match="max_seq_len"):
        streamed(jnp.zeros((1, 257), jnp.int32))
    with pytest.raises(ValueError, match="max_seq_len"):
        streamed.generate(jnp.zeros((1, 250), jnp.int32), max_new_tokens=10)
