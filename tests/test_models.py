"""Model zoo tests: shapes, param counts, TP sharding, training smoke."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import Bert, Llama, build_model, get_config, param_count
from accelerate_tpu.parallel.sharding import PartitionRules, infer_shardings
from accelerate_tpu.state import PartialState
from accelerate_tpu.utils import next_rng_key, set_seed


def test_llama_forward_shape():
    model = Llama("llama-tiny")
    set_seed(0)
    params = model.init(next_rng_key())
    ids = jnp.arange(32).reshape(2, 16) % 1024
    logits = model.apply(params, ids)
    assert logits.shape == (2, 16, 1024)
    assert np.isfinite(np.asarray(logits)).all()


def test_llama_param_count_matches_config():
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    actual = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert actual == param_count(get_config("llama-tiny"))


def test_bert_param_count_matches_config():
    model = Bert("bert-tiny")
    params = model.init(jax.random.key(0))
    actual = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert actual == param_count(get_config("bert-tiny"))


def test_bert_forward_shape():
    model = Bert("bert-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.arange(32).reshape(2, 16) % 1024
    logits = model.apply(params, ids, attention_mask=jnp.ones_like(ids))
    assert logits.shape == (2, 2)


def test_llama_causality():
    """Changing a future token must not affect earlier logits."""
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.arange(16)[None, :] % 1024
    logits1 = model.apply(params, ids)
    ids2 = ids.at[0, -1].set(7)
    logits2 = model.apply(params, ids2)
    np.testing.assert_allclose(np.asarray(logits1[0, :-1]), np.asarray(logits2[0, :-1]), atol=1e-5)


def test_llama_tp_sharding_applied():
    state = PartialState(parallelism=ParallelismConfig(tensor=4))
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    rules = PartitionRules(model.partition_rules())
    shardings = infer_shardings(params, state.mesh, rules)
    wq_spec = shardings["layers"]["wq"].spec
    # leading dim carries the (size-1 here) pipeline axis; last dim is TP
    assert wq_spec == jax.sharding.PartitionSpec("pipeline", None, "tensor")
    wo_spec = shardings["layers"]["wo"].spec
    assert wo_spec == jax.sharding.PartitionSpec("pipeline", "tensor", None)


def test_llama_tp_forward_matches_single_device():
    """TP=4 sharded forward must equal the unsharded forward numerically."""
    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    ids = jnp.arange(32).reshape(2, 16) % 1024
    expected = model.apply(params, ids)

    accelerator = Accelerator(parallelism=ParallelismConfig(tensor=4))
    prepared = accelerator.prepare_model(model, params=params)
    got = prepared(ids)
    np.testing.assert_allclose(np.asarray(expected), np.asarray(got), atol=2e-4)


def test_llama_trains():
    accelerator = Accelerator(parallelism=ParallelismConfig(fsdp=2, tensor=2))
    model = Llama("llama-tiny")
    loss_fn = Llama.loss_fn(model)
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(rng.integers(0, 1024, (8, 32)), jnp.int32)}
    losses = []
    for _ in range(10):
        with accelerator.accumulate(prepared):
            loss = accelerator.backward(loss_fn, batch)
            optimizer.step()
            optimizer.zero_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # memorizing one batch


def test_build_model_registry():
    assert isinstance(build_model("llama-tiny"), Llama)
    assert isinstance(build_model("bert-base"), Bert)
    with pytest.raises(KeyError):
        build_model("gpt-unknown")
