"""Elastic training (resilience/elastic.py; ISSUE 13).

The claims this file pins, each as a measured property rather than prose:

- **The drill** (acceptance) — a chaos-injected data-parallel host loss
  mid-training recovers via the buddy rung with post-recovery params AND
  optimizer state bit-equal a shrink-resumed reference (the checkpoint rung,
  i.e. the PR 11 save→load reshard path), zero steps lost, `{"kind":
  "elastic"}` records + an MTTR metric + a goodput `elastic_reshard` entry
  in the ledger.
- **The ladder** — every rung exercised by its own test: buddy (above),
  checkpoint fallback (no redundancy / stale mirror), and fail-loud
  (:class:`ElasticFailure` when nothing is left to try).
- **The primitive** — mesh shrink N → N−1 data ranks and regrow back, each
  a pure relayout: gathered params/opt state bit-exact across both (pinned
  independently of the chaos drill).
- **Honesty** — :func:`assemble_from_survivors` never reads a shard on a
  lost device, and reports incomplete coverage instead of fabricating data.
- **The dataloader** — prefetched batches globalized before the shrink are
  re-sharded onto the live mesh at consume time; the global example stream
  is unchanged (no example skipped or repeated).
- **The gate** — the resharded step passes the PR 8 contract gate and the
  replication audit on the shrunken mesh (env-mismatched contracts skip,
  never fabricate drift).
- **Satellites** — ZeRO+cpu_offload fallback warns and records instead of
  silently degrading; `estimate-memory --elastic-redundancy` prices the
  buddy mirror; the chaos env vars parse.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from accelerate_tpu import (
    Accelerator,
    ElasticConfig,
    ElasticFailure,
    FaultPlan,
    FullyShardedDataParallelPlugin,
    ResilienceConfig,
    TelemetryConfig,
)
from accelerate_tpu.models import Bert
from accelerate_tpu.resilience.elastic import (
    assemble_from_survivors,
    buddy_mesh,
    host_device_groups,
    relay_tree,
    tree_covered,
)
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.utils.random import set_seed

from jax.sharding import NamedSharding, PartitionSpec as P


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _bert_batch(model, n=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": np.asarray(
            rng.integers(0, model.config.vocab_size, (n, seq)), np.int32
        ),
        "attention_mask": np.ones((n, seq), np.int32),
        "labels": np.asarray(rng.integers(0, 2, (n,)), np.int32),
    }


def _tree_equal(a, b) -> bool:
    return all(jax.tree.leaves(jax.tree.map(np.array_equal, a, b)))


def _gather(tree):
    return jax.tree.map(np.asarray, tree)


def _build(fault_plan=None, telemetry_dir=None, seed=0):
    _reset()
    set_seed(seed)
    accelerator = Accelerator(
        resilience_config=(
            ResilienceConfig(guard=None, fault_plan=fault_plan)
            if fault_plan is not None
            else None
        ),
        telemetry_config=TelemetryConfig(dir=telemetry_dir) if telemetry_dir else None,
    )
    model = Bert("bert-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    return accelerator, model, prepared, optimizer


def _records(telemetry_dir, kind):
    path = os.path.join(telemetry_dir, "telemetry.jsonl")
    with open(path) as f:
        return [r for r in map(json.loads, f) if r.get("kind") == kind]


# ---------------------------------------------------------------------------
# buddy layout / survivor reassembly units
# ---------------------------------------------------------------------------


def test_host_groups_and_buddy_roll_cross_hosts():
    """The buddy of every shard lives on a DIFFERENT host: the roll distance
    is one host's worth of devices, so host loss can never take a shard and
    its mirror together."""
    _reset()
    acc = Accelerator()
    devices = list(acc.mesh.devices.reshape(-1))
    groups = host_device_groups(devices, 2)
    assert [len(g) for g in groups] == [4, 4]
    host_of = {d.id: h for h, group in enumerate(groups) for d in group}
    bmesh = buddy_mesh(acc.mesh, 4)
    primary_flat = list(acc.mesh.devices.reshape(-1))
    buddy_flat = list(bmesh.devices.reshape(-1))
    for p, b in zip(primary_flat, buddy_flat):
        assert host_of[p.id] != host_of[b.id]
    with pytest.raises(ValueError, match="divide"):
        host_device_groups(devices, 3)


def test_assemble_from_survivors_honest_coverage():
    """Reassembly reads ONLY surviving shards; a lost region is filled from
    the buddy, and missing both returns None instead of fabricating data."""
    _reset()
    acc = Accelerator()
    mesh = acc.mesh
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    primary = jax.device_put(x, NamedSharding(mesh, P("data")))
    bmesh = buddy_mesh(mesh, 4)
    buddy = jax.device_put(primary, NamedSharding(bmesh, P("data")))
    flat = list(mesh.devices.reshape(-1))
    lost = {flat[i].id for i in (4, 5, 6, 7)}  # host 1 dies
    # primary alone cannot cover (its shards 4..7 are on lost devices)
    assert assemble_from_survivors(primary, lost) is None
    # with the buddy every region survives, bit-exact
    got = assemble_from_survivors(primary, lost, buddy)
    np.testing.assert_array_equal(got, x)
    # replicated leaves are recoverable from any single survivor
    rep = jax.device_put(jnp.float32(7.5), NamedSharding(mesh, P()))
    assert float(assemble_from_survivors(rep, lost)) == 7.5
    # losing a shard's primary AND buddy hosts → incomplete, reported
    lost_both = lost | {flat[0].id, flat[1].id, flat[2].id, flat[3].id}
    assert assemble_from_survivors(primary, lost_both, buddy) is None
    # the metadata-only coverage pre-check agrees with the data path
    tree = {"w": primary, "s": rep}
    buddies = {"w": buddy, "s": jax.device_put(rep, NamedSharding(bmesh, P()))}
    assert tree_covered(tree, lost, buddies)
    assert not tree_covered(tree, lost_both, buddies)
    # and the per-leaf relay lands the value bit-exact on a survivor mesh
    surv = [d for d in flat if d.id not in lost]
    smesh = jax.sharding.Mesh(
        np.asarray(surv, dtype=object).reshape(4, 1), ("data", "fsdp")
    )
    new_sh = {
        "w": NamedSharding(smesh, P("data")),
        "s": NamedSharding(smesh, P()),
    }
    relayed = relay_tree(tree, lost, buddies, new_sh)
    np.testing.assert_array_equal(np.asarray(relayed["w"]), x)
    assert float(relayed["s"]) == 7.5


def test_elastic_config_validation():
    with pytest.raises(ValueError, match="redundancy"):
        ElasticConfig(redundancy=2)
    with pytest.raises(ValueError, match="mirror_every"):
        ElasticConfig(mirror_every=0)


def test_host_loss_chaos_env_vars(monkeypatch):
    monkeypatch.setenv("ACCELERATE_CHAOS_HOST_LOSS_STEP", "5")
    monkeypatch.setenv("ACCELERATE_CHAOS_HOST_LOSS_INDEX", "1")
    plan = FaultPlan.from_env()
    assert plan is not None and plan.active
    assert plan.host_loss_step == 5
    assert plan.host_loss_index == 1
    # fires exactly once, gated by the validity predicate
    assert plan.host_loss(4) is None
    assert plan.host_loss(5, valid=lambda i: False) is None
    assert plan.host_loss(5) == 1
    assert plan.host_loss(5) is None
    assert any(e["fault"] == "host_loss" for e in plan.events)


# ---------------------------------------------------------------------------
# the chaos drill (acceptance): buddy rung ≡ shrink-resumed reference
# ---------------------------------------------------------------------------


def _drill(tmp_path, redundancy, telemetry_sub, save_step=None):
    """6 steps with host 1 of 2 dying at step boundary 4. ``redundancy=1``
    recovers via the buddy rung; ``redundancy=0`` with ``save_step`` set
    recovers via the checkpoint rung — the shrink-resumed reference, riding
    the PR 11 bit-exact save→load reshard path."""
    tdir = str(tmp_path / telemetry_sub)
    ckpt_dir = str(tmp_path / f"ckpt_{telemetry_sub}")
    plan = FaultPlan(host_loss_step=4, host_loss_index=1)
    accelerator, model, prepared, optimizer = _build(fault_plan=plan, telemetry_dir=tdir)
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model),
        config=ElasticConfig(redundancy=redundancy, num_hosts=2, checkpoint_dir=ckpt_dir),
    )
    batch = _bert_batch(model)
    losses = []
    for i in range(6):
        if save_step is not None and coordinator.completed_steps == save_step:
            accelerator.save_state(
                os.path.join(ckpt_dir, f"checkpoint_{save_step}"),
                manifest_metadata={"step": save_step},
            )
            save_step = None
        losses.append(float(coordinator.step(batch)))
    return accelerator, coordinator, prepared, optimizer, losses, tdir


def test_host_loss_drill_buddy_bit_equal_shrink_resumed_reference(tmp_path):
    acc_a, coord_a, prep_a, opt_a, losses_a, tdir_a = _drill(tmp_path, 1, "buddy")
    assert coord_a.last_recovery["rung"] == "buddy"
    assert coord_a.last_recovery["steps_lost"] == 0
    assert coord_a.last_recovery["mttr_s"] > 0
    assert dict(coord_a.mesh.shape)["data"] == 4

    acc_b, coord_b, prep_b, opt_b, losses_b, _ = _drill(
        tmp_path, 0, "ckpt_reference", save_step=3
    )
    assert coord_b.last_recovery["rung"] == "checkpoint"
    assert coord_b.last_recovery["steps_lost"] == 0  # saved AT the boundary

    # the acceptance gate: post-recovery state bit-equal the reference that
    # resumed onto the same shrunken mesh from disk
    assert _tree_equal(_gather(prep_a.params), _gather(prep_b.params))
    assert _tree_equal(_gather(opt_a.opt_state), _gather(opt_b.opt_state))
    np.testing.assert_array_equal(losses_a, losses_b)

    # observability: detection + recovery records, MTTR, goodput ledger
    elastic_records = _records(tdir_a, "elastic")
    events = [r["event"] for r in elastic_records]
    assert "redundancy_allocated" in events
    assert "host_loss_detected" in events
    recovered = [r for r in elastic_records if r["event"] == "recovered"]
    assert len(recovered) == 1
    assert recovered[0]["rung"] == "buddy"
    assert recovered[0]["mttr_s"] > 0
    assert recovered[0]["mesh"]["data"] == 4
    assert "elastic_reshard" in acc_a.telemetry.goodput._lost
    # the chaos ledger agrees the fault really fired
    assert any(
        e["fault"] == "host_loss" for e in acc_a.resilience.chaos.events
    )


def test_stale_mirror_falls_back_to_checkpoint_rung(tmp_path):
    """mirror_every=4 leaves the mirror refreshed at step 4 while the loss
    lands at boundary 6: a stale buddy must NOT be mixed with fresh survivor
    shards — the ladder records the buddy attempt and degrades to the
    checkpoint rung, losing the steps since the save."""
    tdir = str(tmp_path / "stale")
    ckpt_dir = str(tmp_path / "stale_ckpt")
    plan = FaultPlan(host_loss_step=6, host_loss_index=0)
    accelerator, model, prepared, optimizer = _build(fault_plan=plan, telemetry_dir=tdir)
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model),
        config=ElasticConfig(
            redundancy=1, num_hosts=2, mirror_every=4, checkpoint_dir=ckpt_dir
        ),
    )
    batch = _bert_batch(model)
    for _ in range(3):
        coordinator.step(batch)
    accelerator.save_state(
        os.path.join(ckpt_dir, "checkpoint_3"), manifest_metadata={"step": 3}
    )
    for _ in range(3):
        coordinator.step(batch)
    assert coordinator.last_recovery["rung"] == "checkpoint"
    assert coordinator.last_recovery["tried"] == ["buddy", "checkpoint"]
    assert coordinator.last_recovery["steps_lost"] == 2  # steps 4 and 5
    assert dict(coordinator.mesh.shape)["data"] == 4


def test_ladder_fails_loud_when_nothing_left(tmp_path):
    """No redundancy and no checkpoint: the last rung raises ElasticFailure
    (never silent corruption) and records the failed recovery."""
    tdir = str(tmp_path / "fail")
    plan = FaultPlan(host_loss_step=2, host_loss_index=1)
    accelerator, model, prepared, optimizer = _build(fault_plan=plan, telemetry_dir=tdir)
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model), config=ElasticConfig(redundancy=0, num_hosts=2)
    )
    batch = _bert_batch(model)
    coordinator.step(batch)
    with pytest.raises(ElasticFailure, match="checkpoint_dir|redundancy"):
        coordinator.step(batch)
    assert coordinator.last_recovery["event"] == "recovery_failed"
    assert coordinator.last_recovery["rung"] == "fail"
    failed = [r for r in _records(tdir, "elastic") if r["event"] == "recovery_failed"]
    assert len(failed) == 1 and "reason" in failed[0]


# ---------------------------------------------------------------------------
# the elastic primitive: shrink N → N−1 and regrow, bit-exact (satellite)
# ---------------------------------------------------------------------------


def test_mesh_shrink_n_minus_one_and_regrow_bit_exact(tmp_path):
    """Extends the PR 11 checkpoint-reshard pin to a genuine mesh SHRINK
    (8 → 7 data ranks, where most dims stop dividing and the ZeRO fold
    degrades per-leaf) and REGROW: both are pure relayouts, so gathered
    params and optimizer state are bit-exact across each. Pinned without
    the chaos drill — this is the primitive the drill stands on."""
    ckpt_dir = str(tmp_path / "ckpts")
    accelerator, model, prepared, optimizer = _build(
        telemetry_dir=str(tmp_path / "telemetry")
    )
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model),
        # one host per device: losing host 7 is exactly "N → N−1 data ranks"
        config=ElasticConfig(redundancy=1, num_hosts=8, checkpoint_dir=ckpt_dir),
    )
    batch = _bert_batch(model)
    for _ in range(3):
        coordinator.step(batch)
    reference_params = _gather(prepared.params)
    reference_opt = _gather(optimizer.opt_state)

    report = coordinator.reshard(lost_host=7)
    assert report["rung"] == "buddy"
    assert dict(coordinator.mesh.shape)["data"] == 7
    # every param is still fully materialized across the 7 survivors
    assert _tree_equal(reference_params, _gather(prepared.params))
    assert _tree_equal(reference_opt, _gather(optimizer.opt_state))

    regrown = coordinator.regrow()
    assert regrown["hosts"] == [7]
    assert dict(coordinator.mesh.shape)["data"] == 8
    assert _tree_equal(reference_params, _gather(prepared.params))
    assert _tree_equal(reference_opt, _gather(optimizer.opt_state))
    # and the regrown mesh trains: one more step on the full mesh
    coordinator.step(batch)
    assert coordinator.completed_steps == 4


def test_regrow_after_drill_resumes_training(tmp_path):
    """Full cycle: lose a host, recover via buddy, train shrunken, revive,
    regrow, train full — the state relayouts are bit-exact around the regrow
    and every phase steps."""
    accelerator, coordinator, prepared, optimizer, _, _ = _drill(tmp_path, 1, "cycle")
    before = _gather(prepared.params)
    coordinator.regrow()
    assert dict(coordinator.mesh.shape)["data"] == 8
    assert _tree_equal(before, _gather(prepared.params))
    batch = _bert_batch(Bert("bert-tiny"))
    loss = float(coordinator.step(batch))
    assert np.isfinite(loss)
    # regrow re-arms the mirror on the full mesh
    assert coordinator._buddy is not None


# ---------------------------------------------------------------------------
# dataloader: prefetched batches re-shard onto the live mesh
# ---------------------------------------------------------------------------


def test_prefetched_batches_reglobalize_after_shrink():
    """A batch the prefetch thread globalized BEFORE the shrink is laid out
    for the dead mesh; the consumer must re-shard it from the retained host
    copy — same rows (no example skipped or repeated), live mesh."""
    import dataclasses as dc

    from accelerate_tpu.data_loader import prepare_data_loader

    class Rows:
        def __len__(self):
            return 32

        def __getitem__(self, i):
            return {"x": np.float32(i)}

    _reset()
    accelerator = Accelerator()
    loader = prepare_data_loader(Rows(), batch_size=8, prefetch=2)
    it = iter(loader)
    first = next(it)
    assert first["x"].sharding.mesh == accelerator.mesh
    old_mesh = accelerator.mesh
    # give the producer time to prefetch (and globalize) the next batches
    import time

    time.sleep(0.3)
    # elastic shrink: 4 survivors
    survivors = list(old_mesh.devices.reshape(-1))[:4]
    par = dc.replace(accelerator.state.parallelism, data=4)
    accelerator.state._partial.rebuild_mesh(devices=survivors, parallelism=par)
    second = next(it)
    third = next(it)
    for batch, start in ((second, 8), (third, 16)):
        assert batch["x"].sharding.mesh == accelerator.mesh
        assert batch["x"].sharding.mesh != old_mesh
        np.testing.assert_array_equal(
            np.asarray(batch["x"]), np.arange(start, start + 8, dtype=np.float32)
        )


# ---------------------------------------------------------------------------
# the resharded step passes the contract gate + replication audit (acceptance)
# ---------------------------------------------------------------------------


def test_resharded_step_passes_contract_gate_and_replication_audit(tmp_path):
    contracts_dir = os.path.join(os.path.dirname(__file__), "contracts")
    plan = FaultPlan(host_loss_step=3, host_loss_index=1)
    accelerator, model, prepared, optimizer = _build(
        fault_plan=plan, telemetry_dir=str(tmp_path / "telemetry")
    )
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model),
        config=ElasticConfig(redundancy=1, num_hosts=2, contracts_dir=contracts_dir),
    )
    batch = _bert_batch(model)
    for _ in range(3):
        coordinator.step(batch)  # recovery at boundary 3 runs the gate
    gate = coordinator.last_recovery.get("contract_gate")
    assert gate is not None
    assert gate["errors"] == 0
    # independently: the replication audit asserts sharding intent on the
    # shrunken mesh (ZeRO is still the declared layout on 4 data ranks)
    assert accelerator._zero_update_sharding
    report = accelerator.analyze(
        step=coordinator._step,
        batch=coordinator._batch_struct,
        label="elastic_resharded_step",
        write_record=False,
    )
    assert report.errors == [], report.render()


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------


def test_zero_cpu_offload_fallback_warns_and_records(tmp_path, caplog):
    """ZeRO + cpu_offload used to fall back to the replicated update
    SILENTLY; now it warns with the reason and writes a telemetry record —
    while the stage<3 replicated-params contract stays quiet (explicit,
    documented semantics)."""
    import logging

    tdir = str(tmp_path / "telemetry")
    _reset()
    with caplog.at_level(logging.WARNING):
        accelerator = Accelerator(
            fsdp_plugin=FullyShardedDataParallelPlugin(stage=3, cpu_offload=True),
            telemetry_config=TelemetryConfig(dir=tdir),
        )
    assert not accelerator._zero_update_sharding
    assert any(
        "cpu_offload" in r.message and "replicated update" in r.message
        for r in caplog.records
    )
    accelerator.telemetry.finish()
    records = _records(tdir, "zero")
    assert len(records) == 1
    assert records[0]["event"] == "fallback_replicated"
    assert "cpu_offload" in records[0]["reason"]

    # stage<3 (explicit replicated-params contract) stays silent
    _reset()
    caplog.clear()
    with caplog.at_level(logging.WARNING):
        accelerator = Accelerator(
            fsdp_plugin=FullyShardedDataParallelPlugin(stage=2),
        )
    assert not accelerator._zero_update_sharding
    assert not any("replicated update" in r.message for r in caplog.records)


def test_estimate_memory_elastic_redundancy_column(capsys):
    from accelerate_tpu.commands.cli import main
    from accelerate_tpu.parallel.zero import (
        elastic_redundancy_bytes,
        zero_update_state_bytes,
    )

    rc = main(
        ["estimate-memory", "params=1000000", "--replicas", "8", "--elastic-redundancy", "1"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "+buddy/chip x1" in out
    assert "Buddy column" in out
    # the formula: one mirror of the 1/N param shard + 1/N optimizer shard
    opt_chip, _ = zero_update_state_bytes(1000, 4, 8)
    assert elastic_redundancy_bytes(1000, 4, 8, 1) == opt_chip + 500
    assert elastic_redundancy_bytes(1000, 4, 8, 0) == 0
    # without the flag the column is absent
    rc = main(["estimate-memory", "params=1000000", "--replicas", "8"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "+buddy/chip" not in out


def test_fp16_scaler_survives_shrink_losing_host_zero(tmp_path):
    """The replicated scaler scalars must be re-read from SURVIVORS —
    losing host 0 (the device a naive `np.asarray` would read from) is the
    adversarial case. The scale value crosses the shrink intact and
    training (including a post-shrink overflow skip) keeps working."""

    class LinearModel:
        def init(self, rng):
            del rng
            return {"a": jnp.zeros((), jnp.float32), "b": jnp.zeros((), jnp.float32)}

        @staticmethod
        def apply(params, x):
            return params["a"] * x + params["b"]

    def loss_fn(params, batch):
        return jnp.mean((LinearModel.apply(params, batch["x"]) - batch["y"]) ** 2)

    _reset()
    set_seed(0)
    accelerator = Accelerator(
        mixed_precision="fp16",
        resilience_config=ResilienceConfig(
            guard=None, fault_plan=FaultPlan(host_loss_step=3, host_loss_index=0)
        ),
        telemetry_config=TelemetryConfig(dir=str(tmp_path / "telemetry")),
    )
    model, optimizer = accelerator.prepare(LinearModel(), optax.sgd(0.1))
    coordinator = accelerator.elastic_coordinator(
        loss_fn, config=ElasticConfig(redundancy=1, num_hosts=2)
    )
    batch = {
        "x": np.linspace(-1, 1, 8, dtype=np.float32),
        "y": (2 * np.linspace(-1, 1, 8) + 3).astype(np.float32),
    }
    for _ in range(2):
        coordinator.step(batch)
    scale_before = float(optimizer.scale)
    coordinator.step(batch)  # boundary 3: host 0 dies → buddy reshard
    assert coordinator.last_recovery["rung"] == "buddy"
    assert float(optimizer.scale) == scale_before  # crossed the shrink intact
    # the scaler's overflow-skip semantics still work on the shrunken mesh
    bad = {
        "x": np.ones((8,), np.float32),
        "y": np.full((8,), np.inf, np.float32),
    }
    coordinator.step(bad)
    assert optimizer.step_was_skipped
    assert float(optimizer.scale) < scale_before
    coordinator.step(batch)
    assert not optimizer.step_was_skipped


def test_sigusr1_signal_requests_shrink_and_drill_fires(tmp_path):
    """The pod supervisor's partial-failure signal (SIGUSR1) flags a shrink
    for the next boundary; the coordinator then probes the chaos plan for
    the lost host regardless of the scheduled step — the training-side half
    of `pod-launch --elastic`."""
    import signal

    plan = FaultPlan(host_loss_step=99, host_loss_index=1)  # far future
    accelerator, model, prepared, optimizer = _build(
        fault_plan=plan, telemetry_dir=str(tmp_path / "telemetry")
    )
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model),
        config=ElasticConfig(redundancy=1, num_hosts=2, handle_signals=True),
    )
    batch = _bert_batch(model)
    coordinator.step(batch)
    os.kill(os.getpid(), signal.SIGUSR1)
    assert coordinator._shrink_requested
    coordinator.step(batch)  # boundary probes the plan → host 1 lost now
    assert coordinator.last_recovery is not None
    assert coordinator.last_recovery["rung"] == "buddy"
    assert dict(coordinator.mesh.shape)["data"] == 4


def test_stage2_fsdp_opt_state_stays_sharded_across_reshard(tmp_path):
    """ZeRO stage-1/2 FSDP shards the Adam moments over fsdp while params
    stay replicated (opt_reference_shardings). A reshard must re-derive that
    SAME layout — dropping it would silently re-replicate the optimizer
    state (N× its HBM) after a recovery. The fsdp axis also absorbs the
    shrink here (8 → 4), since it is a weight-update shard axis like data."""
    from accelerate_tpu.telemetry.memory import state_bytes_per_chip

    _reset()
    set_seed(0)
    plan = FaultPlan(host_loss_step=3, host_loss_index=1)
    accelerator = Accelerator(
        fsdp_plugin=FullyShardedDataParallelPlugin(stage=2),
        resilience_config=ResilienceConfig(guard=None, fault_plan=plan),
        telemetry_config=TelemetryConfig(dir=str(tmp_path / "telemetry")),
    )
    assert dict(accelerator.mesh.shape)["fsdp"] == 8
    model = Bert("bert-tiny")
    prepared = accelerator.prepare_model(model)
    optimizer = accelerator.prepare_optimizer(optax.adamw(1e-3))
    full_bytes = sum(
        np.asarray(leaf).nbytes for leaf in jax.tree.leaves(optimizer.opt_state)
    )
    assert state_bytes_per_chip(optimizer.opt_state) < full_bytes  # sharded now
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model), config=ElasticConfig(redundancy=1, num_hosts=2)
    )
    batch = _bert_batch(model)
    for _ in range(3):
        coordinator.step(batch)
    assert coordinator.last_recovery["rung"] == "buddy"
    assert dict(coordinator.mesh.shape)["fsdp"] == 4  # fsdp absorbed the shrink
    # the moments are still sharded on the survivor mesh, not re-replicated
    per_chip = state_bytes_per_chip(optimizer.opt_state)
    assert per_chip < full_bytes, (per_chip, full_bytes)
    specs = [
        s.spec
        for s in jax.tree.leaves(
            optimizer._opt_state_shardings,
            is_leaf=lambda x: hasattr(x, "spec"),
        )
    ]
    assert any("fsdp" in str(spec) for spec in specs)
    coordinator.step(batch)  # and it still trains


def test_infeasible_survivor_mesh_records_recovery_failed(tmp_path, monkeypatch):
    """A loss whose survivors cannot form a mesh must still flow through the
    fail rung — recorded as recovery_failed, never a bare mid-ladder raise
    that leaves last_recovery stale."""
    tdir = str(tmp_path / "telemetry")
    accelerator, model, prepared, optimizer = _build(telemetry_dir=tdir)
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model), config=ElasticConfig(redundancy=1, num_hosts=2)
    )
    monkeypatch.setattr(coordinator, "_shrunk_parallelism", lambda n: None)
    with pytest.raises(ElasticFailure, match="cannot form a training mesh"):
        coordinator.reshard(lost_host=1)
    assert coordinator.last_recovery["event"] == "recovery_failed"
    assert any(
        r["event"] == "recovery_failed" for r in _records(tdir, "elastic")
    )


def test_unresolved_shrink_request_warns_and_records(tmp_path, caplog):
    """request_shrink() with no probe able to name the lost host must not be
    swallowed silently: the run would step toward a hung collective. A
    warning plus a {"kind":"elastic"} record say so."""
    import logging

    tdir = str(tmp_path / "telemetry")
    accelerator, model, prepared, optimizer = _build(telemetry_dir=tdir)
    coordinator = accelerator.elastic_coordinator(
        Bert.loss_fn(model), config=ElasticConfig(redundancy=0, num_hosts=2)
    )
    batch = _bert_batch(model)
    coordinator.step(batch)
    coordinator.request_shrink()
    with caplog.at_level(logging.WARNING):
        coordinator.step(batch)  # no FaultPlan armed: nothing names the host
    assert any("no host probe" in r.message for r in caplog.records)
    assert any(
        r["event"] == "shrink_request_unresolved" for r in _records(tdir, "elastic")
    )
    assert dict(coordinator.mesh.shape)["data"] == 8  # full mesh, run continues


def test_stale_device_batch_never_reads_lost_devices(tmp_path):
    """A device batch still laid out for the pre-shrink mesh must be
    salvaged through SURVIVING shards only — replicated leaves are
    recoverable, data-sharded rows on lost devices raise loudly (a plain
    np.asarray would silently read dead memory in the simulation and hang
    real hardware)."""
    accelerator, coordinator, prepared, optimizer, _, _ = _drill(
        tmp_path, 1, "stalebatch"
    )
    # build stale arrays on the ORIGINAL full mesh
    full_mesh = jax.sharding.Mesh(
        np.asarray(coordinator._full_devices, dtype=object).reshape(8, 1, 1, 1, 1, 1),
        ("data", "fsdp", "pipeline", "expert", "sequence", "tensor"),
    )
    stale_rep = jax.device_put(
        np.ones((8, 16), np.int32), NamedSharding(full_mesh, P())
    )
    salvaged = coordinator.shard_batch({"x": stale_rep})
    assert salvaged["x"].sharding.mesh == coordinator.mesh
    np.testing.assert_array_equal(np.asarray(salvaged["x"]), np.ones((8, 16)))
    stale_sharded = jax.device_put(
        np.arange(8, dtype=np.int32), NamedSharding(full_mesh, P("data"))
    )
    with pytest.raises(ElasticFailure, match="LOST devices"):
        coordinator.shard_batch({"x": stale_sharded})


def test_coordinator_requires_prepared_optimizer(tmp_path):
    _reset()
    set_seed(0)
    accelerator = Accelerator(
        telemetry_config=TelemetryConfig(dir=str(tmp_path))
    )
    model = Bert("bert-tiny")
    accelerator.prepare_model(model)
    with pytest.raises(ValueError, match="prepare_optimizer"):
        accelerator.elastic_coordinator(
            Bert.loss_fn(model), config=ElasticConfig(num_hosts=2)
        )


def test_coordinator_rejects_cpu_offload(tmp_path):
    _reset()
    set_seed(0)
    accelerator = Accelerator(
        parallelism=None,
        fsdp_plugin=FullyShardedDataParallelPlugin(stage=3, cpu_offload=True),
        telemetry_config=TelemetryConfig(dir=str(tmp_path)),
    )
    model = Bert("bert-tiny")
    accelerator.prepare_model(model)
    accelerator.prepare_optimizer(optax.adamw(1e-3))
    with pytest.raises(ValueError, match="cpu_offload"):
        accelerator.elastic_coordinator(
            Bert.loss_fn(model), config=ElasticConfig(num_hosts=2)
        )
