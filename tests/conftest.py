"""Test harness: force an 8-device virtual CPU mesh.

This replaces the reference's debug_launcher/gloo CPU simulation (SURVEY §4):
JAX can split the host CPU into N virtual devices, so every sharding path runs
single-process in CI exactly as it would over 8 TPU chips.
"""

import os

# The surrounding environment may point JAX at real TPU hardware (and
# sitecustomize may have imported jax already, so env vars alone are too
# late) — force the virtual CPU mesh through jax.config before any backend
# initializes.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Subprocess-based tests (examples, launch, multi-process) must import the
# package without it being pip-installed: export the repo root to children.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
os.environ["PYTHONPATH"] = (
    _REPO_ROOT + os.pathsep + os.environ["PYTHONPATH"]
    if os.environ.get("PYTHONPATH")
    else _REPO_ROOT
)

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)  # works even when XLA_FLAGS was read too early
except AttributeError:
    pass  # older jax: XLA_FLAGS above already forced the 8-device host platform

import pytest


@pytest.fixture(autouse=True)
def reset_singletons():
    """Singleton hygiene (reference testing.py:419-431): drop Borg state between tests."""
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
