"""Pod launch / tpu-config command assembly + notebook/debug launchers
(reference commands/launch.py:812-868, commands/tpu.py:90-157,
launchers.py:38-258)."""

import argparse
import subprocess
import sys

import pytest

from accelerate_tpu.commands.cli import main as cli_main
from accelerate_tpu.commands.pod import assemble_worker_command, build_gcloud_ssh_cmd
from accelerate_tpu.commands.tpu import assemble_pod_setup_command


def _pod_args(**over):
    base = dict(
        tpu_name="mypod", tpu_zone="us-central2-b", use_alpha=False, use_sudo=False,
        worker="all", env=[], workdir=None, debug=True, mixed_precision=None,
        num_processes=None, training_script="train.py", training_script_args=[],
    )
    base.update(over)
    return argparse.Namespace(**base)


def test_pod_worker_command_assembly():
    cmd = assemble_worker_command(
        _pod_args(env=["WANDB_MODE=offline"], workdir="/srv/job", mixed_precision="bf16",
                  training_script_args=["--epochs", "3"])
    )
    assert cmd == (
        "cd /srv/job; export WANDB_MODE=offline; export ACCELERATE_IN_TPU_POD=1; "
        "accelerate-tpu launch --mixed_precision bf16 train.py --epochs 3"
    )


def test_pod_worker_command_sudo_and_quoting():
    cmd = assemble_worker_command(_pod_args(use_sudo=True, training_script="my train.py"))
    assert "sudo accelerate-tpu launch 'my train.py'" in cmd


def test_pod_bad_env_raises():
    with pytest.raises(ValueError, match="KEY=VALUE"):
        assemble_worker_command(_pod_args(env=["NOVALUE"]))


def test_gcloud_ssh_cmd():
    cmd = build_gcloud_ssh_cmd("mypod", "us-central2-b", "echo hi", worker="0", use_alpha=True)
    assert cmd == [
        "gcloud", "alpha", "compute", "tpus", "tpu-vm", "ssh", "mypod",
        "--zone", "us-central2-b", "--command", "echo hi", "--worker", "0",
    ]


def test_pod_launch_cli_debug_prints(capsys):
    rc = cli_main([
        "pod-launch", "--tpu_name", "mypod", "--tpu_zone", "us-central2-b",
        "--debug", "train.py", "--", "--epochs", "1",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "gcloud compute tpus tpu-vm ssh mypod" in out
    assert "accelerate-tpu launch train.py" in out


def test_tpu_config_command_assembly(tmp_path, monkeypatch):
    monkeypatch.delenv("ACCELERATE_CONFIG_FILE", raising=False)
    f = tmp_path / "cmds.txt"
    f.write_text("echo one\necho two\n")
    args = argparse.Namespace(
        config_file=None, command=None, command_file=str(f), tpu_name="p", tpu_zone="z",
        worker="all", use_alpha=False, install_accelerate=True, accelerate_version="0.1.0",
        debug=True,
    )
    cmd = assemble_pod_setup_command(args, config={})
    assert cmd == "pip install accelerate-tpu==0.1.0; echo one; echo two"


def test_tpu_config_requires_some_command(monkeypatch):
    monkeypatch.delenv("ACCELERATE_CONFIG_FILE", raising=False)
    args = argparse.Namespace(
        config_file=None, command=None, command_file=None, tpu_name="p", tpu_zone="z",
        worker="all", use_alpha=False, install_accelerate=False, accelerate_version="latest",
        debug=True,
    )
    with pytest.raises(ValueError, match="command"):
        assemble_pod_setup_command(args, config={})


def test_tpu_config_cli_debug_prints(capsys):
    rc = cli_main([
        "tpu-config", "--tpu_name", "p", "--tpu_zone", "z", "--command", "echo hi", "--debug",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "gcloud compute tpus tpu-vm ssh p" in out


def test_verify_checkpoint_cli_ok_and_fail(tmp_path, capsys):
    """`accelerate-tpu verify-checkpoint <dir>` validates a manifest offline:
    exit 0 on a complete checkpoint, 1 (with the problems listed) after
    corruption."""
    from accelerate_tpu.fault_tolerance import build_manifest, write_manifest
    from accelerate_tpu.state import PartialState

    PartialState()
    ckpt = tmp_path / "checkpoint_5"
    ckpt.mkdir()
    (ckpt / "model_0.npz").write_bytes(b"x" * 1024)
    write_manifest(str(ckpt), build_manifest(str(ckpt), step=5))

    assert cli_main(["verify-checkpoint", str(ckpt)]) == 0
    out = capsys.readouterr().out
    assert "OK" in out and "step 5" in out

    (ckpt / "model_0.npz").write_bytes(b"y" * 512)  # corrupt after commit
    assert cli_main(["verify-checkpoint", str(ckpt)]) == 1
    err = capsys.readouterr().err
    assert "size mismatch" in err

    assert cli_main(["verify-checkpoint", str(tmp_path / "missing")]) == 1


def test_verify_checkpoint_cli_no_checksums(tmp_path, capsys):
    from accelerate_tpu.fault_tolerance import build_manifest, write_manifest
    from accelerate_tpu.state import PartialState

    PartialState()
    ckpt = tmp_path / "checkpoint_1"
    ckpt.mkdir()
    (ckpt / "w.bin").write_bytes(b"a" * 64)
    write_manifest(str(ckpt), build_manifest(str(ckpt)))
    (ckpt / "w.bin").write_bytes(b"b" * 64)  # same size, different bytes
    assert cli_main(["verify-checkpoint", "--no-checksums", str(ckpt)]) == 0
    assert cli_main(["verify-checkpoint", str(ckpt)]) == 1


def test_verify_checkpoint_cli_repair(tmp_path, capsys):
    """Satellite (resilience PR): --repair GCs torn .tmp staging dirs and
    prunes checkpoints that fail checksum, printing what was removed; valid
    checkpoints survive and still verify."""
    from accelerate_tpu.fault_tolerance import build_manifest, write_manifest
    from accelerate_tpu.state import PartialState

    PartialState()
    good = tmp_path / "checkpoint_2"
    good.mkdir()
    (good / "w.bin").write_bytes(b"g" * 64)
    write_manifest(str(good), build_manifest(str(good), step=2))
    bad = tmp_path / "checkpoint_1"
    bad.mkdir()
    (bad / "w.bin").write_bytes(b"a" * 64)
    write_manifest(str(bad), build_manifest(str(bad), step=1))
    (bad / "w.bin").write_bytes(b"b" * 64)  # same-size bit rot after commit
    torn = tmp_path / "checkpoint_3.tmp"
    torn.mkdir()
    (torn / "junk.bin").write_bytes(b"x" * 16)

    assert cli_main(["verify-checkpoint", "--repair", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "REMOVED torn staging dir" in out and "checkpoint_3.tmp" in out
    assert "PRUNED invalid checkpoint" in out and str(bad) in out
    assert "OK" in out and str(good) in out
    assert good.exists() and not bad.exists() and not torn.exists()
    # idempotent: a second repair finds nothing to remove
    assert cli_main(["verify-checkpoint", "--repair", str(tmp_path)]) == 0
    assert "PRUNED" not in capsys.readouterr().out
    # base-dir verify without --repair keeps reporting
    assert cli_main(["verify-checkpoint", str(tmp_path)]) == 0


def test_notebook_launcher_runs_inline():
    from accelerate_tpu import notebook_launcher

    result = notebook_launcher(lambda a, b: a + b, args=(2, 3), mixed_precision="bf16")
    assert result == 5


def test_notebook_launcher_rejects_bad_precision():
    from accelerate_tpu import notebook_launcher

    with pytest.raises(ValueError, match="mixed_precision"):
        notebook_launcher(lambda: None, mixed_precision="int8")


def test_debug_launcher_simulates_devices():
    from accelerate_tpu import debug_launcher
    from accelerate_tpu.test_utils.training import device_count_smoke

    out = debug_launcher(device_count_smoke, args=(4,), num_processes=4)
    assert "devices=4" in out


def test_tpu_config_honors_env_config_file(tmp_path, monkeypatch, capsys):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text("tpu_name: envpod\ntpu_zone: envzone\ncommands:\n  - echo from-env\n")
    monkeypatch.setenv("ACCELERATE_CONFIG_FILE", str(cfg))
    rc = cli_main(["tpu-config", "--debug"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "envpod" in out and "echo from-env" in out


def test_debug_launcher_main_defined_function(tmp_path):
    script = tmp_path / "train_debug.py"
    script.write_text(
        "from accelerate_tpu import debug_launcher\n"
        "def my_fn(n):\n"
        "    import jax\n"
        "    assert jax.device_count() == n\n"
        "    print(f'main-fn devices={jax.device_count()}')\n"
        "if __name__ == '__main__':\n"
        "    out = debug_launcher(my_fn, args=(2,), num_processes=2)\n"
        "    print(out)\n"
    )
    result = subprocess.run([sys.executable, str(script)], capture_output=True, text=True, timeout=300)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "main-fn devices=2" in result.stdout
