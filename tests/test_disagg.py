"""Disaggregated prefill/decode serving: transactional live-KV handoff.

The acceptance drills for the prefill/decode split (docs/serving.md,
"Disaggregated serving"), all tier-1-fast on CPU: a request prefilled on a
prefill-pool replica completes on a decode-pool replica via live KV handoff
with output bit-equal to a single engine at temperature 0; chaos
``handoff_loss`` / mid-handoff prefill-replica kill still end every request
in exactly one terminal state via re-prefill fallback (bit-equal too); a
dead prefill pool degrades to mixed-mode serving instead of QueueFull-ing
the fleet; and steady state compiles nothing per pool, the adopt/copy
programs included.
"""

import json

import numpy as np
import pytest

import jax

from accelerate_tpu.models import Llama
from accelerate_tpu.models.generation import generate
from accelerate_tpu.resilience import FaultPlan, is_handoff_transient
from accelerate_tpu.serving import (
    HandoffLost,
    QueueFull,
    ReplicaLost,
    ReplicaState,
    ServingEngine,
    ServingRouter,
    run_offered_load,
)
from accelerate_tpu.telemetry import CompileTracker
from accelerate_tpu.telemetry.serving import ServingStats, fleet_rollup


@pytest.fixture(scope="module")
def llama():
    model = Llama("llama-tiny")
    return model, model.init(jax.random.key(0))


def _prompts(lengths, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (s,)).astype(np.int32) for s in lengths]


def _expected(llama, prompts, max_new_tokens, eos=None):
    model, params = llama
    return [
        np.asarray(
            generate(model, params, p[None], max_new_tokens=max_new_tokens, eos_token_id=eos)
        )[0][p.size :]
        for p in prompts
    ]


def _disagg(llama, roles=("prefill", "decode"), fault_plan=None, telemetry=None,
            **engine_kwargs):
    model, params = llama
    kwargs = {"num_slots": 2, "max_len": 64, **engine_kwargs}
    return ServingRouter(
        engine_factory=lambda: ServingEngine(model, params, **kwargs),
        num_replicas=len(roles),
        roles=list(roles),
        fault_plan=fault_plan,
        telemetry=telemetry,
    )


# -- the acceptance invariants ------------------------------------------------


def test_disagg_generate_bit_equal_single_engine(llama):
    """The headline contract: a request admitted on the prefill pool and
    completed on the decode pool via live KV handoff is bit-equal to one
    engine at temperature 0 — the handoff is token-exact, so disaggregation
    is invisible in the output."""
    model, params = llama
    prompts = _prompts([3, 7, 12, 5, 9, 4])
    single = ServingEngine(model, params, num_slots=2, max_len=64, eos_token_id=5)
    ref = single.generate_many(prompts, max_new_tokens=6)
    router = _disagg(llama, eos_token_id=5)
    outs = router.generate_many(prompts, max_new_tokens=6)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, b)
    # every request genuinely moved through the handoff, none re-prefilled
    assert router.kv_handoffs == len(prompts)
    m = router.metrics()
    assert m["handoffs_adopted"] == len(prompts)
    assert m["handoff_fallbacks"] == 0
    assert m["requests_parked"] == len(prompts)
    assert m["requests_adopted"] == len(prompts)
    assert m["handoff_pages_moved"] >= len(prompts)
    assert m["handoff_bytes_moved"] > 0
    assert m["handoff_p99_ms"] > 0
    # the transaction left nothing behind: source pages all released
    assert router.replicas[0].engine.parked_count == 0
    assert router.replicas[0].engine.cache.pages_in_use == 0


def test_prefill_kill_mid_stream_falls_back_bit_equal(llama, tmp_path):
    """Chaos kills the prefill replica mid-stream — parked KV and all. Every
    request still reaches exactly one terminal state (fallback re-prefill on
    the decode pool, bit-equal at temp 0), and the decode survivor is
    promoted to mixed so the fleet keeps serving."""
    from accelerate_tpu.telemetry import Telemetry, TelemetryConfig

    hub = Telemetry(config=TelemetryConfig(dir=str(tmp_path)))
    prompts = _prompts([3, 7, 12, 5, 9, 4], seed=1)
    exp = _expected(llama, prompts, 6)
    plan = FaultPlan(replica_kill_step=2, replica_kill_index=0)
    router = _disagg(llama, fault_plan=plan, telemetry=hub)
    rids = [router.submit(p, max_new_tokens=6) for p in prompts]

    results = []  # via step(), not run(): a dict would hide duplicates
    while router.busy:
        results.extend(router.step())
    assert router.replica_deaths == 1
    assert router.replicas[0].state is ReplicaState.DEAD
    assert router.replicas[1].role == "mixed"  # pool degradation kicked in
    seen = [r.request_id for r in results if r.request_id in set(rids)]
    assert sorted(seen) == sorted(rids)  # all terminated, none twice
    by_id = {r.request_id: r for r in results}
    assert all(by_id[rid].finish_reason == "length" for rid in rids)
    for i, rid in enumerate(rids):
        np.testing.assert_array_equal(by_id[rid].generated, exp[i])

    router.flush_telemetry()
    hub.finish(flush=False)
    records = [json.loads(line) for line in open(tmp_path / "telemetry.jsonl")]
    handoffs = [r for r in records if r.get("event") == "kv_handoff"]
    # the seam's BEHAVIOR is observable: every record carries an outcome
    assert handoffs and all(
        r["outcome"] in ("adopted", "retried", "fell_back") for r in handoffs
    )
    degraded = [r for r in records if r.get("event") == "pool_degraded"]
    assert degraded and degraded[0]["pool"] == "prefill"


def test_handoff_loss_retries_then_falls_back(llama):
    """Chaos loses the source blocks on attempts 0-2 (one request's whole
    retry budget): the handoff retries — each retry DEFERRED behind its
    jittered not-before stamp, never an in-step sleep — then falls back to
    re-prefill on the decode pool, and the request still completes
    bit-equal: never stranded, never duplicated. Once the loss schedule is
    exhausted, later requests adopt normally."""
    prompts = _prompts([5, 8, 6], seed=2)
    exp = _expected(llama, prompts, 5)
    plan = FaultPlan(handoff_loss_at=(0, 1, 2))
    router = _disagg(llama, fault_plan=plan)
    # one request at a time makes the fleet-global attempt indices land on
    # ONE request's budget: 3 losses → 2 retries + 1 fallback
    rid0 = router.submit(prompts[0], max_new_tokens=5)
    results = []
    while router.busy:
        results.extend(router.step())
    m = router.metrics()
    assert m["handoffs_retried"] == 2  # attempts 1 and 2 were retries
    assert m["handoff_fallbacks"] == 1  # budget spent → re-prefill
    assert m["handoffs_adopted"] == 0
    # the survivors (loss schedule exhausted) hand off normally
    rids = [rid0] + [router.submit(p, max_new_tokens=5) for p in prompts[1:]]
    while router.busy:
        results.extend(router.step())
    by_id = {r.request_id: r for r in results}
    assert sorted(by_id) == sorted(rids)
    for i, rid in enumerate(rids):
        np.testing.assert_array_equal(by_id[rid].generated, exp[i])
    assert router.metrics()["handoffs_adopted"] == len(prompts) - 1
    assert [e["fault"] for e in plan.events] == ["handoff_loss"] * 3
    # fallback released the parked pages: nothing pinned at the source
    assert router.replicas[0].engine.parked_count == 0
    assert router.replicas[0].engine.cache.pages_in_use == 0


def test_handoff_stall_times_out_and_recovers(llama):
    """A stalled transfer past ``handoff_timeout_s`` reads as lost: the
    attempt retries (jittered policy) and the next, unstalled attempt
    adopts — TTFT absorbs the stall, correctness doesn't."""
    prompts = _prompts([6], seed=3)
    exp = _expected(llama, prompts, 4)
    plan = FaultPlan(handoff_stall_at=(0,), stall_seconds=0.05)
    router = _disagg(llama, fault_plan=plan)
    router.handoff_timeout_s = 0.01  # the stall overshoots this
    rids = [router.submit(p, max_new_tokens=4) for p in prompts]
    results = router.run()
    np.testing.assert_array_equal(results[rids[0]].generated, exp[0])
    m = router.metrics()
    assert m["handoffs_retried"] == 1
    assert m["handoffs_adopted"] == 1
    assert m["handoff_fallbacks"] == 0
    assert [e["fault"] for e in plan.events] == ["handoff_stall"]


def test_retry_backoff_not_burned_across_destinations(llama):
    """With several decode replicas, a failed transfer must NOT retry
    instantly against the next destination: the jittered backoff stamp
    gates ALL destinations, so one blip costs one attempt per backoff
    window — not the whole budget in a single step."""
    prompts = _prompts([6], seed=13)
    exp = _expected(llama, prompts, 4)
    plan = FaultPlan(handoff_loss_at=(0,))
    router = _disagg(llama, roles=("prefill", "decode", "decode"), fault_plan=plan)
    rid = router.submit(prompts[0], max_new_tokens=4)
    router.step()  # prefill + park
    router.step()  # first handoff attempt: lost → backoff scheduled
    m = router.metrics()
    assert m["handoffs_attempted"] == 1  # NOT one per decode replica
    assert m["handoffs_retried"] == 1 and m["handoff_fallbacks"] == 0
    results = router.run()  # the gated retry fires after the backoff, adopts
    np.testing.assert_array_equal(results[rid].generated, exp[0])
    final = router.metrics()
    assert final["handoffs_adopted"] == 1
    assert final["handoff_fallbacks"] == 0


def test_drained_source_with_dead_decode_pool_finishes_in_place(llama):
    """The livelock regression: KV parked on a DRAINING source while the
    decode pool dies — no placeable destination can ever exist (promotion
    covers only placeable survivors) and the drain is pinned open by the
    parked pages. The request must finish ON its own source, like any
    active slot a drain runs to completion, and the drain then completes."""
    prompts = _prompts([6], seed=14)
    exp = _expected(llama, prompts, 4)
    router = _disagg(llama)
    rid = router.submit(prompts[0], max_new_tokens=4)
    router.step()  # prefill + park on replica 0
    assert router.replicas[0].engine.parked_count == 1
    router.drain_replica(0)
    router._on_replica_death(router.replicas[1], "test kill")
    results = {}
    for _ in range(500):  # bounded: a livelock must fail, not hang pytest
        if not router.busy:
            break
        for r in router.step():
            results[r.request_id] = r
    assert rid in results, "request stranded — drain/handoff livelock"
    np.testing.assert_array_equal(results[rid].generated, exp[0])
    assert router.replicas[0].engine.parked_count == 0
    assert router.replicas[0].state is ReplicaState.DEAD
    assert router.replicas[0].death_reason == "drained"


def test_all_prefill_pool_dead_degrades_to_mixed(llama):
    """Losing the whole prefill pool must not QueueFull the fleet: the
    decode survivors go mixed and serve end to end (slower — no pool
    separation — but serving)."""
    router = _disagg(llama, roles=("prefill", "prefill", "decode"))
    router._on_replica_death(router.replicas[0], "test kill")
    assert router.replicas[2].role == "decode"  # one prefill replica remains
    router._on_replica_death(router.replicas[1], "test kill")
    assert router.replicas[2].role == "mixed"  # now the pool is gone
    prompts = _prompts([4, 6], seed=4)
    exp = _expected(llama, prompts, 4)
    rids = [router.submit(p, max_new_tokens=4) for p in prompts]
    results = router.run()
    for i, rid in enumerate(rids):
        assert results[rid].finish_reason == "length"
        np.testing.assert_array_equal(results[rid].generated, exp[i])
    assert router.kv_handoffs == 0  # mixed serving, no pools left to hand between


def test_decode_pool_dead_resumes_parked_locally(llama):
    """The symmetric degradation: the decode pool dies while KV sits parked
    on the prefill replica. The source goes mixed and RESUMES its own parked
    pages (src == dst handoff: zero copies), completing bit-equal."""
    prompts = _prompts([6], seed=5)
    exp = _expected(llama, prompts, 4)
    router = _disagg(llama)
    rid = router.submit(prompts[0], max_new_tokens=4)
    router.step()  # prefill + park on replica 0
    assert router.replicas[0].engine.parked_count == 1
    router._on_replica_death(router.replicas[1], "test kill")
    assert router.replicas[0].role == "mixed"
    results = router.run()
    np.testing.assert_array_equal(results[rid].generated, exp[0])
    m = router.metrics()
    assert m["handoffs_adopted"] == 1
    assert m["handoff_pages_moved"] >= 1
    assert m["handoff_bytes_moved"] == 0  # resumed in place: nothing moved
    assert router.replicas[0].engine.parked_count == 0


def test_disagg_zero_steady_state_recompiles_per_pool(llama):
    """After warmup, disaggregated traffic — prefill spans, parks, block
    extractions, adoptions, decode — compiles NOTHING in either pool: the
    extract/adopt-copy programs are keyed only on page_shape and warmed with
    everything else."""
    _, params = llama
    model = Llama("llama-tiny")  # fresh instance: clean jit cache
    router = ServingRouter(
        engine_factory=lambda: ServingEngine(
            model, params, num_slots=2, max_len=64, buckets=(8, 16, 32)
        ),
        num_replicas=2,
        roles=["prefill", "decode"],
    )
    tracker = CompileTracker().start()
    router.warmup()
    warm = tracker.snapshot()
    router.generate_many(_prompts([3, 9, 20, 31, 6, 14], seed=6), max_new_tokens=4)
    steady = tracker.snapshot()
    tracker.stop()
    assert router.kv_handoffs == 6  # the handoff path really ran
    assert steady["compile_count"] == warm["compile_count"]
    assert steady["jit_cache_misses"] == warm["jit_cache_misses"]
    assert steady["jit_cache_hits"] > warm["jit_cache_hits"]


# -- transactional bookkeeping ------------------------------------------------


def test_cancelled_parked_request_releases_pages(llama):
    """A cancel landing while the KV sits parked terminates the request as
    'cancelled' exactly once AND releases the parked pages — a cancelled
    handoff must not pin source HBM forever."""
    router = _disagg(llama)
    rid = router.submit(_prompts([6], seed=7)[0], max_new_tokens=8)
    router.step()  # prefill + park
    src = router.replicas[0].engine
    assert src.parked_count == 1
    assert router.cancel(rid)
    results = router.run()
    assert results[rid].finish_reason == "cancelled"
    assert src.parked_count == 0
    assert src.cache.pages_in_use == 0
    assert router.kv_handoffs == 0


def test_draining_prefill_replica_waits_for_parked_handoffs(llama):
    """An operator drain of the prefill replica must not destroy parked KV:
    the replica stays DRAINING (pages readable) until the pending handoff
    acks, and only then completes its drain."""
    router = _disagg(llama)
    rid = router.submit(_prompts([6], seed=8)[0], max_new_tokens=4)
    router.step()  # prefill + park on replica 0
    assert router.replicas[0].engine.parked_count == 1
    router.drain_replica(0)
    # parked KV pins the drain open — not DEAD yet
    assert router.replicas[0].state is ReplicaState.DRAINING
    results = router.run()
    assert results[rid].finish_reason == "length"
    assert router.kv_handoffs == 1  # the handoff still happened, KV intact
    assert router.replicas[0].state is ReplicaState.DEAD
    assert router.replicas[0].death_reason == "drained"


def test_adopt_kv_rejects_token_inexact_and_mismatched_layouts(llama):
    """adopt_kv is the transaction's verification point: a layout that does
    not cover exactly the prompt's prefill (token-exactness), or one from a
    differently-shaped pool, is refused with ValueError — fatal, so the
    router skips retries and re-prefills instead of adopting wrong KV."""
    model, params = llama
    src = ServingEngine(model, params, num_slots=2, max_len=64)
    dst = ServingEngine(model, params, num_slots=2, max_len=64)
    p = _prompts([6], seed=9)[0]
    rid = src.submit(p, max_new_tokens=4, prefill_only=True)
    src.run()
    layout = src.kv_page_layout(rid)
    assert layout["parked"] and layout["length"] == p.size - 1
    kb, vb = src.extract_pages(layout["pages"])
    with pytest.raises(ValueError, match="token-exact"):
        dst.adopt_kv(p[:-1], 4, layout, kb, vb)  # wrong prompt for this KV
    bad = dict(layout, page_size=layout["page_size"] * 2)
    with pytest.raises(ValueError, match="page_size mismatch"):
        dst.adopt_kv(p, 4, bad, kb, vb)
    bad = dict(layout, page_shape=(1, 2, 3))
    with pytest.raises(ValueError, match="page_shape mismatch"):
        dst.adopt_kv(p, 4, bad, kb, vb)
    # the happy path still works after the rejections, and is token-exact
    arid = dst.adopt_kv(p, 4, layout, kb, vb, request_id=rid)
    assert src.release_parked(rid)
    out = dst.run()
    exp = _expected(llama, [p], 4)[0]
    np.testing.assert_array_equal(out[arid].generated, exp)


def test_saturated_decode_pool_defers_handoff_not_fallback(llama):
    """Destination backpressure DEFERS a handoff (parked KV waits, retried
    next fleet step) instead of burning the retry budget or re-prefilling:
    with a 2-lane decode pool and 6 requests, every one still moves by
    handoff — zero fallbacks."""
    prompts = _prompts([3, 7, 12, 5, 9, 4], seed=10)
    router = _disagg(llama)
    rids = [router.submit(p, max_new_tokens=6) for p in prompts]
    results = router.run()
    assert sorted(results) == sorted(rids)
    m = router.metrics()
    assert m["handoffs_adopted"] == len(prompts)
    assert m["handoff_fallbacks"] == 0


def test_offered_load_accounting_exact_under_disaggregation(llama):
    """The loadgen's books stay exact through the pools: every offered
    request is completed (the "prefilled" hop is internal — never surfaced
    as a terminal result), sheds equal retries at drain."""
    prompts = _prompts([3, 5, 7, 4, 6, 3, 5, 4], seed=11)
    router = _disagg(llama, max_queue=16)
    point = run_offered_load(router, prompts, max_new_tokens=5)
    assert point["offered_requests"] == 8
    assert point["requests_completed"] == 8
    assert point["loadgen_sheds"] == point["loadgen_retries"]
    assert point["handoffs_adopted"] + point["handoff_fallbacks"] >= 1
    assert point["requests_parked"] >= point["handoffs_adopted"]


def test_disagg_with_chaos_loadgen_accounting(llama):
    """The serve-bench drill shape: offered load through the pools while
    chaos kills the prefill replica — completed+shed+expired still accounts
    for every offered request."""
    plan = FaultPlan(replica_kill_step=3, replica_kill_index=0)
    router = _disagg(llama, fault_plan=plan, max_queue=16)
    prompts = _prompts([3, 5, 7, 4, 6, 3], seed=12)
    point = run_offered_load(router, prompts, max_new_tokens=5)
    assert point["offered_requests"] == 6
    assert point["requests_completed"] == 6
    assert point["replica_deaths"] == 1
    assert point["loadgen_sheds"] == point["loadgen_retries"]


# -- telemetry / config plumbing ---------------------------------------------


def test_fleet_rollup_handoff_economy_and_pools():
    """Handoff counters sum; latency percentiles merge over raw samples;
    per-pool occupancy groups by role."""
    a, b = ServingStats(2, num_pages=9, page_size=16), ServingStats(2, num_pages=9, page_size=16)
    a.record_handoff_attempt()
    a.record_handoff_attempt()
    a.record_handoff_retry()
    a.record_handoff(pages=2, bytes_moved=4096, seconds=0.010)
    a.record_handoff_fallback()
    b.record_handoff_attempt()
    b.record_handoff(pages=1, bytes_moved=1024, seconds=0.100)
    a.record_parked()
    b.record_adopted()
    a.record_step(0.01, active=1, waiting=0, pages_in_use=4)
    b.record_step(0.01, active=2, waiting=0, pages_in_use=2)
    out = fleet_rollup([a, b], roles=["prefill", "decode"])
    assert out["handoffs_attempted"] == 3
    assert out["handoffs_retried"] == 1
    assert out["handoffs_adopted"] == 2
    assert out["handoff_fallbacks"] == 1
    assert out["handoff_pages_moved"] == 3
    assert out["handoff_bytes_moved"] == 5120
    assert out["requests_parked"] == 1 and out["requests_adopted"] == 1
    # merged p99 sits in b's slow sample, far above a's own
    assert out["handoff_p99_ms"] > 50
    assert out["pool_prefill_replicas"] == 1 and out["pool_decode_replicas"] == 1
    assert out["pool_prefill_slot_occupancy"] == 0.5
    assert out["pool_decode_slot_occupancy"] == 1.0
    assert out["pool_prefill_page_occupancy"] == 0.5
    # single-engine snapshots carry the same keys (zero), diffable column-wise
    snap = ServingStats(2).snapshot()
    for key in ("handoffs_attempted", "handoffs_adopted", "handoff_fallbacks",
                "handoff_pages_moved", "handoff_bytes_moved", "requests_parked",
                "requests_adopted"):
        assert snap[key] == 0


def test_handoff_chaos_env_vars(monkeypatch):
    """The handoff faults arm from the environment like every other chaos
    leg, so an unmodified serve script can be drilled."""
    monkeypatch.setenv("ACCELERATE_CHAOS_HANDOFF_STALL_AT", "0,2")
    monkeypatch.setenv("ACCELERATE_CHAOS_HANDOFF_LOSS_AT", "1")
    plan = FaultPlan.from_env()
    assert plan is not None and plan.active
    assert plan.handoff_stall(0) == plan.stall_seconds
    assert plan.handoff_stall(1) is None
    assert plan.handoff_loss(1) is True
    assert plan.handoff_loss(0) is False
    assert [e["fault"] for e in plan.events] == [
        "handoff_stall", "handoff_loss"
    ]


def test_handoff_transient_classifier():
    """Lost transfers, saturated destinations, and dying replicas retry;
    incompatible pool geometry fails fast to the re-prefill ladder."""
    assert is_handoff_transient(HandoffLost("blocks gone"))
    assert is_handoff_transient(QueueFull("no lane", queue_depth=2))
    assert is_handoff_transient(ReplicaLost("dead", replica_index=0))
    assert not is_handoff_transient(ValueError("page_shape mismatch"))


def test_disagg_config_validation(llama):
    """Roles must cover both phases, match the replica count, and ride on
    paged engines (the dense slab has no page-granular KV to relay)."""
    model, params = llama
    with pytest.raises(ValueError, match="at least one"):
        _disagg(llama, roles=("prefill", "prefill"))
    with pytest.raises(ValueError, match="names 3 replicas"):
        ServingRouter(
            engine_factory=lambda: ServingEngine(model, params, num_slots=2, max_len=64),
            num_replicas=2,
            roles=["prefill", "decode", "mixed"],
        )
    with pytest.raises(ValueError, match="dense"):
        ServingRouter(
            engine_factory=lambda: ServingEngine(
                model, params, num_slots=2, max_len=64, paged=False
            ),
            num_replicas=2,
            roles=["prefill", "decode"],
        )
    with pytest.raises(ValueError, match="paged engine"):
        ServingEngine(model, params, num_slots=2, max_len=64, paged=False).submit(
            np.arange(4, dtype=np.int32), 4, prefill_only=True
        )
