"""Resilience subsystem (ISSUE 4 tentpole): chaos fault injection, fused
numerical guards (skip / escalate / restore), the unified RetryPolicy, and
the chaos end-to-end acceptance run — all on the CPU mesh."""

import errno
import json
import os

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu import Accelerator, CheckpointManager
from accelerate_tpu.fault_tolerance import verify_checkpoint
from accelerate_tpu.resilience import (
    FaultPlan,
    GuardPolicy,
    ResilienceConfig,
    RetryPolicy,
    tree_all_finite,
)
from accelerate_tpu.resilience import retry as retry_mod
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.telemetry import TelemetryConfig


class Tiny:
    def init(self, rng):
        return {"w": jax.random.normal(rng, (8, 4), jnp.float32)}

    @staticmethod
    def apply(params, x):
        return x @ params["w"]


def _loss(params, batch):
    return jnp.mean(Tiny.apply(params, batch) ** 2)


BATCH = jnp.ones((8, 8), jnp.float32)


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _guarded_accelerator(plan=None, policy=None, telemetry_dir=None, **acc_kwargs):
    config = ResilienceConfig(
        guard=policy if policy is not None else GuardPolicy(check_every=2),
        fault_plan=plan,
    )
    telemetry = (
        TelemetryConfig(dir=telemetry_dir, sample_every=2) if telemetry_dir else None
    )
    acc = Accelerator(resilience_config=config, telemetry_config=telemetry, **acc_kwargs)
    model = acc.prepare_model(Tiny(), params=Tiny().init(jax.random.key(0)))
    opt = acc.prepare_optimizer(optax.sgd(1e-2))
    return acc, model, opt


def _clean_params(n_steps: int) -> np.ndarray:
    """Final weights of a fault-free run of ``n_steps`` (same init/batch)."""
    acc = Accelerator()
    model = acc.prepare_model(Tiny(), params=Tiny().init(jax.random.key(0)))
    acc.prepare_optimizer(optax.sgd(1e-2))
    step = acc.compiled_step(_loss)
    for _ in range(n_steps):
        step(BATCH)
    return np.asarray(jax.device_get(model.params["w"]))


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


def test_retry_policy_jittered_backoff_and_hook():
    calls = {"n": 0}
    sleeps = []
    notes = []
    policy = RetryPolicy(max_attempts=3, base_delay=1.0, max_delay=8.0, jitter=0.5,
                         sleep=sleeps.append)
    previous = retry_mod.retry_hook
    retry_mod.retry_hook = lambda *args: notes.append(args)
    try:
        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError(errno.EIO, "Input/output error")
            return "ok"

        assert policy.call(flaky) == "ok"
    finally:
        retry_mod.retry_hook = previous
    assert calls["n"] == 3
    # jitter bounds: delay_for(i) = base·2^i scaled by 1 ± jitter
    assert len(sleeps) == 2
    assert 0.5 <= sleeps[0] <= 1.5
    assert 1.0 <= sleeps[1] <= 3.0
    # every backoff was reported (op, attempt, delay, error)
    assert [n[0] for n in notes] == ["flaky", "flaky"]
    assert [n[1] for n in notes] == [1, 2]


def test_retry_policy_custom_classifier_gates_retries():
    calls = {"n": 0}
    policy = RetryPolicy(
        max_attempts=3, classify=lambda e: isinstance(e, KeyError), sleep=lambda s: None
    )

    def always_keyerror():
        calls["n"] += 1
        raise KeyError("transient-ish")

    with pytest.raises(KeyError):
        policy.call(always_keyerror)
    assert calls["n"] == 3  # classified retryable: all attempts burned

    calls["n"] = 0

    def valueerror():
        calls["n"] += 1
        raise ValueError("real bug")

    with pytest.raises(ValueError):
        policy.call(valueerror)
    assert calls["n"] == 1  # not retryable: propagates immediately


def test_retry_policy_delay_caps_at_max_delay():
    policy = RetryPolicy(base_delay=1.0, max_delay=4.0, jitter=0.0)
    assert [policy.delay_for(i) for i in range(5)] == [1.0, 2.0, 4.0, 4.0, 4.0]


def test_retry_hook_failure_never_breaks_the_retry():
    def bad_hook(*args):
        raise RuntimeError("observer bug")

    previous = retry_mod.retry_hook
    retry_mod.retry_hook = bad_hook
    calls = {"n": 0}
    try:
        policy = RetryPolicy(max_attempts=2, sleep=lambda s: None)

        def flaky():
            calls["n"] += 1
            if calls["n"] < 2:
                raise OSError(errno.EIO, "Input/output error")
            return "ok"

        assert policy.call(flaky) == "ok"
    finally:
        retry_mod.retry_hook = previous


# ---------------------------------------------------------------------------
# FaultPlan
# ---------------------------------------------------------------------------


def test_fault_plan_from_env(monkeypatch):
    assert FaultPlan.from_env() is None  # no chaos vars → no plan
    monkeypatch.setenv("ACCELERATE_CHAOS_NAN_STEPS", "3, 7")
    monkeypatch.setenv("ACCELERATE_CHAOS_NAN_TARGET", "loss")
    monkeypatch.setenv("ACCELERATE_CHAOS_IO_FAILURES", "2")
    monkeypatch.setenv("ACCELERATE_CHAOS_SIGTERM_STEP", "9")
    monkeypatch.setenv("ACCELERATE_CHAOS_STALL_STEPS", "4")
    monkeypatch.setenv("ACCELERATE_CHAOS_SERVING_BURST_STEP", "2")
    monkeypatch.setenv("ACCELERATE_CHAOS_SERVING_BURST_SIZE", "5")
    plan = FaultPlan.from_env()
    assert plan.nan_steps == (3, 7)
    assert plan.nan_target == "loss"
    assert plan.io_failures == 2
    assert plan.sigterm_step == 9
    assert plan.stall_steps == (4,)
    assert plan.serving_burst_step == 2 and plan.serving_burst_size == 5
    assert plan.active
    # chaos env arms the whole subsystem
    assert ResilienceConfig.from_env().enabled


def test_fault_plan_io_budget_is_finite():
    plan = FaultPlan(io_failures=2)
    with pytest.raises(OSError):
        plan.probe_io("checkpoint_save")
    with pytest.raises(OSError):
        plan.probe_io("checkpoint_save")
    plan.probe_io("checkpoint_save")  # budget spent: no-op
    assert [e["fault"] for e in plan.events] == ["io_error", "io_error"]


def test_fault_plan_rejects_bad_nan_target():
    with pytest.raises(ValueError, match="nan_target"):
        FaultPlan(nan_target="params")


def test_tree_all_finite():
    assert bool(tree_all_finite({"a": jnp.ones(3), "b": jnp.zeros(2)}))
    assert not bool(tree_all_finite({"a": jnp.ones(3), "b": jnp.asarray(jnp.nan)}))
    assert bool(tree_all_finite({"ints": jnp.arange(3)}))  # non-float leaves ignored


# ---------------------------------------------------------------------------
# numerical guards (fused into compiled_step)
# ---------------------------------------------------------------------------


def test_guard_skips_nan_steps_bit_exactly():
    """6 guarded steps with NaN injected at 2 and 5 produce EXACTLY the
    params of a fault-free 4-step run: skip-and-log applies no update and
    perturbs nothing else."""
    clean = _clean_params(4)
    _reset()
    plan = FaultPlan(nan_steps=(2, 5))
    acc, model, opt = _guarded_accelerator(plan=plan)
    step = acc.compiled_step(_loss)
    for _ in range(6):
        step(BATCH)
    guard = acc.resilience.guard
    guard.check(model, opt)  # flush the final window
    assert guard.skipped_steps == 2
    np.testing.assert_array_equal(
        clean, np.asarray(jax.device_get(model.params["w"]))
    )


def test_guard_detects_loss_nan_target():
    plan = FaultPlan(nan_steps=(2,), nan_target="loss")
    acc, model, opt = _guarded_accelerator(plan=plan)
    step = acc.compiled_step(_loss)
    losses = [float(step(BATCH)) for _ in range(4)]
    guard = acc.resilience.guard
    guard.check(model, opt)
    assert guard.skipped_steps == 1
    assert np.isnan(losses[1]) and not np.isnan(losses[3])  # the report is honest


def test_guard_escalates_clip_after_bad_step():
    """For escalate_steps after a bad step the global-norm clip tightens to
    escalate_clip: with a near-zero escalation the post-NaN updates are
    frozen, unlike the unescalated control."""
    plan = FaultPlan(nan_steps=(2,))
    policy = GuardPolicy(check_every=2, escalate_clip=1e-8, escalate_steps=4)
    acc, model, opt = _guarded_accelerator(plan=plan, policy=policy)
    step = acc.compiled_step(_loss)
    step(BATCH)
    after_1 = np.asarray(jax.device_get(model.params["w"]))
    step(BATCH)  # NaN: skipped, escalation armed
    step(BATCH)  # escalated clip ≈ 0 → update ≈ 0
    after_3 = np.asarray(jax.device_get(model.params["w"]))
    np.testing.assert_allclose(after_3, after_1, atol=1e-6)
    state = {k: int(v) for k, v in jax.device_get(acc.resilience.guard.state).items()}
    assert state["escalate"] == 3  # armed at 4 on the bad step, one good step since
    # control: without escalation the step-3 update moves the weights
    _reset()
    acc2, model2, opt2 = _guarded_accelerator(plan=FaultPlan(nan_steps=(2,)))
    step2 = acc2.compiled_step(_loss)
    step2(BATCH)
    control_1 = np.asarray(jax.device_get(model2.params["w"]))
    step2(BATCH)
    step2(BATCH)
    control_3 = np.asarray(jax.device_get(model2.params["w"]))
    assert np.abs(control_3 - control_1).max() > 1e-4


def test_guard_restores_last_known_good_after_k_consecutive_bad_steps(tmp_path):
    """restore_after consecutive bad steps at a check boundary roll params AND
    opt_state back to the rolling snapshot."""
    plan = FaultPlan(nan_steps=(3, 4))
    policy = GuardPolicy(check_every=4, restore_after=2, snapshot_every=1)
    acc, model, opt = _guarded_accelerator(
        plan=plan, policy=policy, telemetry_dir=str(tmp_path)
    )
    initial = np.asarray(jax.device_get(model.params["w"]))
    step = acc.compiled_step(_loss)
    for _ in range(4):  # good, good, NaN, NaN → check at 4 sees consecutive=2
        step(BATCH)
    guard = acc.resilience.guard
    assert guard.restores == 1
    # the snapshot was armed at step 1 (before any update): restore rolled
    # the two good steps back too — last KNOWN good, conservatively
    np.testing.assert_array_equal(
        initial, np.asarray(jax.device_get(model.params["w"]))
    )
    state = {k: int(v) for k, v in jax.device_get(guard.state).items()}
    assert state["consecutive"] == 0 and state["escalate"] == 0
    # training continues healthily from the restored state
    for _ in range(2):
        step(BATCH)
    guard.check(model, opt)
    assert guard.restores == 1
    np.testing.assert_array_equal(
        _clean_params_from(initial, 2), np.asarray(jax.device_get(model.params["w"]))
    )
    acc.end_training()
    records = [json.loads(l) for l in open(tmp_path / "telemetry.jsonl")]
    events = [r.get("event") for r in records if r["kind"] == "resilience"]
    assert "guard_restore" in events and "guard_skip" in events


def _clean_params_from(initial: np.ndarray, n_steps: int) -> np.ndarray:
    """Fault-free reference continuing from ``initial`` weights."""
    _reset()
    acc = Accelerator()
    model = acc.prepare_model(Tiny(), params={"w": jnp.asarray(initial)})
    acc.prepare_optimizer(optax.sgd(1e-2))
    step = acc.compiled_step(_loss)
    for _ in range(n_steps):
        step(BATCH)
    return np.asarray(jax.device_get(model.params["w"]))


def test_guard_skipped_time_feeds_goodput_ledger(tmp_path):
    plan = FaultPlan(nan_steps=(2,))
    acc, model, opt = _guarded_accelerator(plan=plan, telemetry_dir=str(tmp_path))
    step = acc.compiled_step(_loss)
    for _ in range(4):
        loss = step(BATCH)
        acc.telemetry.step(loss)
    acc.resilience.guard.check(model, opt)
    snapshot = acc.telemetry.goodput.snapshot(acc.telemetry.timer.productive_seconds)
    assert snapshot["event_counts"].get("guard_skipped") == 1


def test_resilience_disabled_is_inert():
    acc = Accelerator()
    assert acc.resilience.enabled is False
    assert acc.resilience.guard is None and acc.resilience.chaos is None
    model = acc.prepare_model(Tiny(), params=Tiny().init(jax.random.key(0)))
    acc.prepare_optimizer(optax.sgd(1e-2))
    step = acc.compiled_step(_loss)
    assert np.isfinite(float(step(BATCH)))
    acc.end_training()  # finish() is a no-op, never raises


def test_chaos_stall_injects_host_delay():
    import time as _time

    plan = FaultPlan(stall_steps=(2,), stall_seconds=0.15)
    acc, model, opt = _guarded_accelerator(plan=plan)
    step = acc.compiled_step(_loss)
    step(BATCH)
    start = _time.perf_counter()
    step(BATCH)
    assert _time.perf_counter() - start >= 0.15
    assert [e["fault"] for e in plan.events] == ["stall"]


# ---------------------------------------------------------------------------
# serving chaos: queue-pressure burst → shedding
# ---------------------------------------------------------------------------


def test_serving_burst_forces_load_shedding():
    from accelerate_tpu.models import Llama
    from accelerate_tpu.serving import QueueFull, ServingEngine

    model = Llama("llama-tiny")
    params = model.init(jax.random.key(0))
    plan = FaultPlan(serving_burst_step=0, serving_burst_size=3)
    engine = ServingEngine(
        model, params, num_slots=1, max_len=32, max_queue=2, fault_plan=plan
    )
    prompt = np.arange(1, 5, dtype=np.int32)
    engine.submit(prompt, max_new_tokens=2)
    engine.step()  # burst fires: 3 synthetic requests bypass admission
    assert [e["fault"] for e in plan.events] == ["serving_burst"]
    assert engine.scheduler.waiting >= 3
    with pytest.raises(QueueFull) as exc_info:
        engine.submit(prompt, max_new_tokens=2)
    assert exc_info.value.retry_after_s > 0
    results = engine.run()  # the burst drains; the engine stays healthy
    assert engine.stats.requests_completed >= 4
    assert all(r.finish_reason in ("length", "eos") for r in results.values())


# ---------------------------------------------------------------------------
# the chaos end-to-end acceptance run
# ---------------------------------------------------------------------------


def test_chaos_end_to_end_nan_io_sigterm_resume(tmp_path, monkeypatch):
    """The acceptance scenario: a 12-step training run absorbs 2 NaN steps,
    1 transient checkpoint-save failure, and a SIGTERM — and finishes with
    EXACTLY the weights of a fault-free 10-step run, via a bit-exact resume,
    with every event visible as a resilience record in telemetry.jsonl."""
    monkeypatch.setattr("accelerate_tpu.utils.memory.time.sleep", lambda s: None)
    telemetry_dir = str(tmp_path / "telemetry")
    ckpt_dir = str(tmp_path / "ckpts")
    TOTAL, SIGTERM_AT = 12, 5

    # ---- phase 1: NaN at step 3, SIGTERM at step 5, the preemption save's
    # manifest write hits one injected transient EIO and must retry through it
    plan1 = FaultPlan(nan_steps=(3,), sigterm_step=SIGTERM_AT, io_failures=1)
    acc, model, opt = _guarded_accelerator(plan=plan1, telemetry_dir=telemetry_dir)
    step = acc.compiled_step(_loss)
    with CheckpointManager(acc, checkpoint_dir=ckpt_dir) as manager:
        last = 0
        for i in range(1, TOTAL + 1):
            loss = step(BATCH)
            acc.telemetry.step(loss)
            last = i
            if manager.save_on_preemption(step=i):
                break
    assert last == SIGTERM_AT  # the SIGTERM ended the run at its boundary save
    assert manager.exit_requested
    injected = [e["fault"] for e in plan1.events]
    assert injected == ["nan", "sigterm", "io_error"]
    # the save retried through the injected failure and committed verifiably
    target = os.path.join(ckpt_dir, f"checkpoint_{SIGTERM_AT}")
    assert verify_checkpoint(target) == []
    phase1_final = np.asarray(jax.device_get(model.params["w"]))
    acc.end_training()

    # ---- phase 2: auto-resume, then NaN at global step 7 (local step 2)
    _reset()
    plan2 = FaultPlan(nan_steps=(2,))
    acc2, model2, opt2 = _guarded_accelerator(plan=plan2, telemetry_dir=telemetry_dir)
    # junk init on purpose: resume must overwrite it bit-exactly
    model2.params = {"w": jnp.zeros_like(model2.params["w"])}
    manager2 = CheckpointManager(acc2, checkpoint_dir=ckpt_dir, handle_signals=())
    resume = manager2.resume("auto")
    assert resume is not None and resume.step == SIGTERM_AT
    np.testing.assert_array_equal(
        phase1_final, np.asarray(jax.device_get(model2.params["w"]))
    )  # bit-exact resume
    step2 = acc2.compiled_step(_loss)
    for i in range(SIGTERM_AT + 1, TOTAL + 1):
        loss = step2(BATCH)
        acc2.telemetry.step(loss)
    guard2 = acc2.resilience.guard
    guard2.check(model2, opt2)
    faulty_final = np.asarray(jax.device_get(model2.params["w"]))
    faulty_loss = float(_loss(jax.device_get(model2.params), np.asarray(BATCH)))
    acc2.end_training()

    # ---- the invariant: 12 faulty steps with 2 skips == 10 clean steps
    skips = acc2.resilience.guard.skipped_steps + 1  # phase2 + phase1's one skip
    assert skips == 2
    _reset()
    clean_final = _clean_params(TOTAL - skips)
    np.testing.assert_array_equal(clean_final, faulty_final)
    clean_loss = float(_loss({"w": jnp.asarray(clean_final)}, np.asarray(BATCH)))
    assert clean_loss == faulty_loss

    # ---- telemetry.jsonl carries the whole story as resilience records
    records = [json.loads(l) for l in open(os.path.join(telemetry_dir, "telemetry.jsonl"))]
    res = [r for r in records if r["kind"] == "resilience"]
    faults = [r for r in res if r.get("event") == "fault_injected"]
    assert sum(1 for r in faults if r["fault"] == "nan") == 2
    assert sum(1 for r in faults if r["fault"] == "io_error") == 1
    assert sum(1 for r in faults if r["fault"] == "sigterm") == 1
    skip_records = [r for r in res if r.get("event") == "guard_skip"]
    assert sum(r["count"] for r in skip_records) == 2  # matches the injection plan
    assert any(r.get("event") == "retry" for r in res)  # the backoff was recorded
    assert any(r.get("event") == "summary" for r in res)
